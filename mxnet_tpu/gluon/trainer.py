"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py).

Bridges Parameters ↔ KVStore ↔ Optimizer: ``step(batch_size)`` does the
gradient allreduce (if multi-replica / multi-host) then the optimizer update,
mirroring the reference's ``_allreduce_grads`` + ``_update`` flow
(SURVEY §3.2). The TPU fast path — gradients reduced by ``psum`` *inside*
the jitted step over ICI — lives in mxnet_tpu.parallel; this Trainer is the
eager/compatibility path and is exactly what the reference's API promises.
"""
from __future__ import annotations

import numpy as np

from .. import kvstore as kvs
from .. import optimizer as opt
from ..base import MXNetError
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("Trainer expects a ParameterDict or list of "
                             "Parameters")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise MXNetError(f"invalid parameter {param!r}")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        self._contains_sparse = False
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._optimizer_applied_on_kv = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError("optimizer_params must be None when "
                                 "optimizer is an Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if self._kv_initialized:
            return
        if self._kvstore_type is None or self._kvstore_type is False:
            self._kvstore = None
        else:
            kv = self._kvstore_type if isinstance(self._kvstore_type,
                                                  kvs.KVStore) else \
                kvs.create(self._kvstore_type)
            multi_replica = any(len(p.list_ctx()) > 1 for p in self._params
                                if p.grad_req != "null")
            multi_host = kv.num_workers > 1
            if not multi_replica and not multi_host and \
                    not self._update_on_kvstore:
                kv = None  # single device, single host: pure local update
            self._kvstore = kv
            if kv is not None:
                update_on_kv = self._update_on_kvstore
                if update_on_kv is None:
                    update_on_kv = kv.type.startswith("dist")
                if self._compression_params:
                    kv.set_gradient_compression(self._compression_params)
                for i, param in enumerate(self._params):
                    if param.grad_req != "null":
                        kv.init(i, param.data(param.list_ctx()[0]))
                if update_on_kv:
                    kv.set_optimizer(self._optimizer)
                    self._optimizer_applied_on_kv = True
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _check_grads(self):
        for param in self._params:
            if param.grad_req != "null" and param._grad is None:
                raise MXNetError(
                    f"parameter {param.name} has no gradient buffer — run "
                    f"forward inside autograd.record() and call backward() "
                    f"before step()")

    def step(self, batch_size, ignore_stale_grad=False):
        """rescale by 1/batch_size, allreduce, update (ref: Trainer.step)."""
        self._init_kvstore()
        self._check_grads()
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler is not None:
            from ..contrib.amp import amp_dtype
            if amp_dtype() != "float16":
                # bf16 has fp32 exponent range: scale overflow cannot
                # trigger — skip the per-step finiteness sync entirely
                scaler = None
        if scaler is not None:
            # fp16 AMP: a non-finite gradient means the loss scale
            # overflowed — skip this update and halve the scale
            # (ref: amp.py DynamicLossScaler + the trainer patch
            # amp.init_trainer installs). The scale change only affects
            # the NEXT scale_loss; this step's grads carry the old scale.
            # Multi-host: the decision must be GLOBAL — an early return on
            # one host while peers enter the allreduce would hang the
            # collective (and diverge loss scales), so OR the flag across
            # processes first.
            overflow = scaler.has_overflow(self._params)
            import jax
            if jax.process_count() > 1:
                import jax.numpy as jnp
                from jax.experimental import multihost_utils
                flags = multihost_utils.process_allgather(
                    jnp.asarray([overflow]))
                overflow = bool(np.asarray(flags).any())
            if overflow:
                scaler.update_scale(True)
                return
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)
        if scaler is not None:
            scaler.update_scale(False)

    def allreduce_grads(self):
        self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if getattr(param, "_grad_stype", "default") == "row_sparse" \
                    and any(getattr(g, "_sparse", None) is not None
                            for g in param.list_grad()):
                raise MXNetError(
                    f"parameter {param.name}: row-sparse gradients with a "
                    f"reducing kvstore (multi-replica / update_on_kvstore) "
                    f"are not supported — use kvstore=None (single device) "
                    f"or dense gradients; the dense buffer here would push "
                    f"stale zeros")
            grads = param.list_grad()
            if self._optimizer_applied_on_kv:
                self._kvstore.push(i, grads)
                self._kvstore.pull(i, out=param.list_data())
            else:
                self._kvstore.push(i, grads)
                self._kvstore.pull(i, out=grads)

    def update(self, batch_size, ignore_stale_grad=False):
        self._init_kvstore()
        self._check_grads()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._optimizer_applied_on_kv:
            return  # weights were updated on the kvstore and pulled back
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            for upd, arr, grad in zip(
                    self._updaters * len(param.list_data()),
                    param.list_data(), param.list_grad()):
                g = grad
                if getattr(param, "_grad_stype", "default") \
                        == "row_sparse":
                    rs = getattr(grad, "_sparse", None)
                    if rs is not None and \
                            not getattr(grad, "_sparse_used", False):
                        g = rs    # touched-rows-only update. The view
                        # stays readable (param.grad()) but is marked
                        # consumed so a step without a fresh backward
                        # doesn't re-apply it (the dense path's stale
                        # grad is the zero buffer).
                        grad._sparse_used = True
                    elif rs is not None:
                        continue  # stale sparse grad: nothing new to apply
                upd(i, g, arr)

    def save_states(self, fname):
        """ref: Trainer.save_states — optimizer/updater state checkpoint."""
        self._init_kvstore()
        if self._optimizer_applied_on_kv:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            from ..resilience.atomic import atomic_write
            with atomic_write(fname, "wb") as f:
                f.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        self._init_kvstore()
        if self._optimizer_applied_on_kv:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                self._updaters[0].set_states(f.read())
            self._optimizer = self._updaters[0].optimizer

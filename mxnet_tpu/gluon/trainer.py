"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py).

Bridges Parameters ↔ KVStore ↔ Optimizer: ``step(batch_size)`` does the
gradient allreduce (if multi-replica / multi-host) then the optimizer update,
mirroring the reference's ``_allreduce_grads`` + ``_update`` flow
(SURVEY §3.2). The TPU fast path — gradients reduced by ``psum`` *inside*
the jitted step over ICI — lives in mxnet_tpu.parallel; this Trainer is the
eager/compatibility path and is exactly what the reference's API promises.

Anomaly guardrails (docs/guardrails.md): the finiteness decision is made
from the POST-allreduce gradients with one fused device-side reduction
(``guardrails.fused.guard_stats``) and a single scalar fetch — the old
per-step ``has_overflow`` per-gradient host pull is gone. Multi-process,
the scalar verdict is OR-reduced in one small allgather whose
participation never depends on rank-local state (kvstore type, whether
this rank passed a loss): every rank skips or none does, and no rank
can wedge a peer by sitting out the collective (the hang class an early
return out of a collective could hit).
"""
from __future__ import annotations

import numpy as np

from .. import kvstore as kvs
from .. import optimizer as opt
from ..base import MXNetError
from ..guardrails.monitor import (AnomalyMonitor, GuardConfig,
                                  handle_divergence)
from ..observability import instrument as _obs
from .parameter import Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None, guard=None):
        if isinstance(params, (dict,)) or hasattr(params, "values"):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("Trainer expects a ParameterDict or list of "
                             "Parameters")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise MXNetError(f"invalid parameter {param!r}")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        self._contains_sparse = False
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._optimizer_applied_on_kv = False
        self._guard_cfg = GuardConfig.coerce(guard)
        if self._guard_cfg is not None \
                and self._guard_cfg.mode == "deferred":
            # the fused trainers carry in-program skip counters that a
            # later guard_poll() can read; the eager path decides every
            # step on the host, so deferred's zero-read contract cannot
            # hold here — reject instead of silently running step-mode
            raise MXNetError(
                "GuardConfig(mode='deferred') needs a fused trainer "
                "(parallel.ShardedTrainer / PipelinedTrainer): the "
                "eager Trainer makes its skip decision on the host "
                "every step — use mode='step' (docs/guardrails.md)")
        self._monitor = (AnomalyMonitor(self._guard_cfg,
                                        consumer="gluon_trainer")
                         if self._guard_cfg is not None else None)
        self._step_count = 0
        self._skipped_steps = 0

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError("optimizer_params must be None when "
                                 "optimizer is an Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        if self._kv_initialized:
            return
        if self._kvstore_type is None or self._kvstore_type is False:
            self._kvstore = None
        else:
            kv = self._kvstore_type if isinstance(self._kvstore_type,
                                                  kvs.KVStore) else \
                kvs.create(self._kvstore_type)
            multi_replica = any(len(p.list_ctx()) > 1 for p in self._params
                                if p.grad_req != "null")
            multi_host = kv.num_workers > 1
            if not multi_replica and not multi_host and \
                    not self._update_on_kvstore:
                kv = None  # single device, single host: pure local update
            self._kvstore = kv
            if kv is not None:
                update_on_kv = self._update_on_kvstore
                if update_on_kv is None:
                    update_on_kv = kv.type.startswith("dist")
                if self._compression_params:
                    kv.set_gradient_compression(self._compression_params)
                for i, param in enumerate(self._params):
                    if param.grad_req != "null":
                        kv.init(i, param.data(param.list_ctx()[0]))
                if update_on_kv:
                    kv.set_optimizer(self._optimizer)
                    self._optimizer_applied_on_kv = True
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _check_grads(self):
        for param in self._params:
            if param.grad_req != "null" and param._grad is None:
                raise MXNetError(
                    f"parameter {param.name} has no gradient buffer — run "
                    f"forward inside autograd.record() and call backward() "
                    f"before step()")

    def _active_scaler(self):
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler is not None:
            from ..contrib.amp import amp_dtype
            if amp_dtype() != "float16":
                # bf16 has fp32 exponent range: scale overflow cannot
                # trigger — skip the per-step finiteness check entirely
                scaler = None
        return scaler

    def _grad_arrays(self, first_replica_only=False):
        """Every live gradient AS THE UPDATE WILL CONSUME IT: the dense
        buffer normally, but the retained row-sparse view
        (``RowSparseNDArray``) when one is deposited — the dense buffer
        under a sparse deposit is still zeros, so guarding/clipping it
        would leave the rows ``_update`` actually applies unchecked. A
        consumed (stale) sparse view contributes nothing, matching
        ``_update`` applying nothing.

        ``first_replica_only=True`` is the post-allreduce view: with a
        reducing kvstore every replica holds the identical reduced
        gradient, so summing all of them would inflate the guard's
        global norm by ``sqrt(num_replicas)`` (wrong clip threshold,
        wrong journaled norm) — one replica per parameter is the true
        norm. Finiteness is unaffected either way."""
        out = []
        for p in self._params:
            if p.grad_req == "null":
                continue
            gs = []
            for g in (p._grad or ()):
                if g is None:
                    continue
                rs = getattr(g, "_sparse", None)
                if rs is None:
                    gs.append(g)
                elif not getattr(g, "_sparse_used", False):
                    gs.append(rs)
            if first_replica_only and gs:
                gs = gs[:1]
            out.extend(gs)
        return out

    def _grad_datas(self, first_replica_only=False):
        """`_grad_arrays` as raw arrays (the fused guard's view) —
        row-sparse views contribute their stored rows (host-resident;
        the device put is the cost of not guarding blind there)."""
        from ..ndarray.sparse import RowSparseNDArray
        return [g.data if isinstance(g, RowSparseNDArray) else g._data
                for g in self._grad_arrays(first_replica_only)]

    def step(self, batch_size, ignore_stale_grad=False, loss=None):
        """rescale by 1/batch_size, allreduce, update (ref: Trainer.step).

        With fp16 AMP and/or a :class:`~mxnet_tpu.guardrails.GuardConfig`
        attached, the finiteness decision rides ONE fused device-side
        reduction over the post-allreduce gradients (module docstring):
        a non-finite step skips the update (params/optimizer state
        untouched — ref: amp.py DynamicLossScaler skip-step), journals a
        ``nonfinite_grad`` record, halves the loss scale if one is
        active, and counts against the divergence budget.

        ``loss`` (optional, any loss NDArray — its mean is taken
        device-side) feeds the monitor's sustained-loss-spike divergence
        detection, folded into the guard's single host fetch. The fused
        trainers read the loss in-program; this eager path can only see
        it if the caller passes it — without it, only the
        consecutive-skip budget can trigger divergence here."""
        self._init_kvstore()
        self._check_grads()
        scaler = self._active_scaler()
        cfg = self._guard_cfg
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._optimizer_applied_on_kv:
            self._reject_clip_on_kv(cfg)
            # update-on-kvstore applies the optimizer DURING push, so
            # the decision must precede the reduce: fused check over the
            # local pre-push grads, OR-reduced across processes (the one
            # remaining allgather — the local-update path below has
            # none). Without this the guard would be silently inert on
            # the kv path: a NaN push corrupts the params on the store.
            self._step_count += 1
            with _obs.trace.span("gluon_trainer.step",
                                 step=self._step_count, on_kvstore=True):
                if (scaler is not None or cfg is not None) \
                        and not self._prepush_guard_ok(scaler, loss):
                    return
                with _obs.step_phase("gluon_trainer", "allreduce"):
                    self._allreduce_grads()
                if scaler is not None:
                    scaler.update_scale(False)
            return
        self._step_count += 1
        # telemetry (docs/observability.md): always-on phase summaries
        # (host clock only), spans under MXNET_TPU_TRACE
        with _obs.trace.span("gluon_trainer.step", step=self._step_count):
            with _obs.step_phase("gluon_trainer", "allreduce"):
                self._allreduce_grads()
            if scaler is not None or cfg is not None:
                # the flag must be agreed across processes: a non-dist
                # kvstore leaves grads rank-local (one rank skipping while
                # its peers update would silently fork params and
                # loss-scale trajectories), and a caller-passed loss is
                # per-rank local either way (a rank-local spike verdict
                # would roll back one rank alone) — _fetch_guard OR-reduces
                # unconditionally multi-process
                with _obs.step_phase("gluon_trainer", "guard_fetch"):
                    ok, gn, loss_v, gnorm_dev = self._fetch_guard(
                        self._grad_datas(first_replica_only=self._kvstore
                                         is not None),
                        loss)
                if not self._note_guard_outcome(ok, gn, scaler, loss_v):
                    return
                self._apply_guard_clip(gnorm_dev)
            with _obs.step_phase("gluon_trainer", "update"):
                self._update(ignore_stale_grad)
            if scaler is not None:
                scaler.update_scale(False)

    def _apply_guard_clip(self, gnorm_dev):
        """Global-norm clip reusing the guard's already-computed device
        norm: the threshold compares the EFFECTIVE (rescaled) gradient
        norm, and clip_global_norm skips its own reduction pass. Shared
        by step() and the manual update() flow."""
        cfg = self._guard_cfg
        if cfg is None or cfg.clip_norm is None:
            return
        from . import utils as gutils
        gutils.clip_global_norm(
            self._grad_arrays(),
            cfg.clip_norm / max(self._optimizer.rescale_grad, 1e-30),
            check_isfinite=False, global_norm=gnorm_dev)

    @staticmethod
    def _reject_clip_on_kv(cfg):
        if cfg is not None and cfg.clip_norm is not None:
            raise MXNetError(
                "GuardConfig.clip_norm is not supported on the "
                "update-on-kvstore path: the optimizer runs on the "
                "store during push, before a global norm over the "
                "REDUCED gradient exists to clip against — construct "
                "the Trainer with update_on_kvstore=False")

    @staticmethod
    def _loss_scalar(loss):
        """Caller-supplied loss as a traced fp32 mean scalar (None in →
        None out) — joins the guard's existing single host fetch."""
        if loss is None:
            return None
        import jax.numpy as jnp
        return jnp.mean(jnp.asarray(getattr(loss, "_data", loss))
                        .astype(jnp.float32))

    def _fetch_guard(self, grads, loss):
        """One fused reduction + ONE host fetch of this step's guard
        view. Multi-process, the flag is OR-reduced and the loss
        mean-reduced in a single small allgather so every rank reaches
        the same skip AND spike verdicts — participation is
        UNCONDITIONAL (never gated on the kvstore type or on whether
        this rank passed ``loss``): a rank-dependent decision to enter
        the collective is itself the deadlock class the guard exists to
        kill, so ranks may disagree about ``loss`` (a has-loss slot
        scopes the mean to the ranks that sent one) but never about
        participating. Returns ``(ok, global_norm, loss_mean_or_None,
        global_norm_device)`` — the device norm is for clip_global_norm
        reuse."""
        import jax

        from ..guardrails import fused
        loss_dev = self._loss_scalar(loss)
        finite_dev, gnorm_dev = fused.guard_stats(grads, loss=loss_dev)
        if jax.process_count() > 1:
            import jax.numpy as jnp
            from jax.experimental import multihost_utils
            # the gather vector is built DEVICE-side (fetching the
            # scalars first only to re-upload them for the collective
            # would double the per-step host round trips) and carries
            # the norm too, so the gathered result is this step's one
            # host read
            vec = np.asarray(multihost_utils.process_allgather(
                jnp.stack([jnp.where(finite_dev, 0.0, 1.0)
                           .astype(jnp.float32),
                           (loss_dev if loss_dev is not None
                            else jnp.float32(0.0)),
                           jnp.float32(0.0 if loss_dev is None else 1.0),
                           gnorm_dev])
            )).reshape(jax.process_count(), 4)
            ok = not vec[:, 0].any()
            senders = vec[:, 2].sum()
            # EVERY rank adopts the senders' loss mean — including
            # ranks that passed no loss: the spike-divergence verdict
            # is computed per-rank from this value, and a rank whose
            # monitor never sees the loss would keep training while its
            # peers roll back or raise (params fork / hang)
            loss_v = (float(vec[:, 1].sum() / senders) if senders > 0
                      else None)
            return ok, float(vec[jax.process_index(), 3]), loss_v, \
                gnorm_dev
        if loss_dev is not None:
            ok, gn, loss_v = fused.host_fetch(finite_dev, gnorm_dev,
                                              loss_dev)
        else:
            (ok, gn), loss_v = fused.host_fetch(finite_dev,
                                                gnorm_dev), None
        return ok, gn, loss_v, gnorm_dev

    def _note_guard_outcome(self, ok, gn, scaler, loss=None):
        """The skip/ok protocol shared by both step() paths: counters,
        loss-scale feedback, monitor observation, divergence handling.
        Returns True when the update may proceed — False on a skipped
        step OR a spike-triggered rollback (the pending gradients belong
        to the abandoned trajectory either way)."""
        if scaler is not None and gn is not None:
            # journal the UNscaled norm — parity with the fused trainers,
            # and stable across loss-scale halvings. _scale is the live
            # truth about what the grads carry: 1/loss_scale while
            # amp.scale_loss's scaling is still on them, 1.0 once
            # amp.unscale() has divided it back out (dividing by
            # loss_scale here again would understate the norm scale-fold)
            gn = gn * self._scale
        if ok:
            if self._monitor is not None:
                verdict = self._monitor.observe(self._step_count, True,
                                                loss=loss, grad_norm=gn)
                if verdict == "diverged":    # sustained finite-loss spike
                    self._handle_divergence()
                    return False
            return True
        self._skipped_steps += 1
        if scaler is not None:
            scaler.update_scale(True)
        if self._monitor is not None:
            verdict = self._monitor.observe(self._step_count, False,
                                            loss=loss, grad_norm=gn)
            if verdict == "diverged":
                self._handle_divergence()
        else:
            from ..guardrails.monitor import journal_scaler_only_skip
            journal_scaler_only_skip(self._step_count, gn, loss,
                                     "gluon_trainer",
                                     total_skips=self._skipped_steps)
        return False

    def _prepush_guard_ok(self, scaler, loss=None):
        """Pre-push finiteness decision for the update-on-kvstore path:
        one fused reduction over the local grads (all replicas — they
        are NOT yet reduced), flag OR-reduced + loss mean-reduced across
        processes so every rank reaches the same verdicts. Returns True
        when the push may proceed."""
        ok, gn, loss_v, _ = self._fetch_guard(self._grad_datas(), loss)
        return self._note_guard_outcome(ok, gn, scaler, loss_v)

    def allreduce_grads(self):
        self._init_kvstore()
        scaler = self._active_scaler()
        cfg = self._guard_cfg
        if self._optimizer_applied_on_kv \
                and (scaler is not None or cfg is not None):
            # manual flow on the update-on-kvstore path: the optimizer
            # runs on the store DURING this push, so the guard decision
            # must happen here, pre-push, exactly as in step() — a
            # skipped push IS the skip-step (update() applies nothing)
            self._reject_clip_on_kv(cfg)
            self._check_grads()
            self._step_count += 1
            if not self._prepush_guard_ok(scaler):
                return
            self._allreduce_grads()
            if scaler is not None:
                scaler.update_scale(False)
            return
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if getattr(param, "_grad_stype", "default") == "row_sparse" \
                    and any(getattr(g, "_sparse", None) is not None
                            for g in param.list_grad()):
                raise MXNetError(
                    f"parameter {param.name}: row-sparse gradients with a "
                    f"reducing kvstore (multi-replica / update_on_kvstore) "
                    f"are not supported — use kvstore=None (single device) "
                    f"or dense gradients; the dense buffer here would push "
                    f"stale zeros")
            grads = param.list_grad()
            if self._optimizer_applied_on_kv:
                self._kvstore.push(i, grads)
                self._kvstore.pull(i, out=param.list_data())
            else:
                self._kvstore.push(i, grads)
                self._kvstore.pull(i, out=grads)

    def update(self, batch_size, ignore_stale_grad=False):
        """Second half of the manual flow (``allreduce_grads();
        update()`` — gradient accumulation). Guarded identically to
        step(): with fp16 AMP or a GuardConfig attached, a non-finite
        gradient skips the update, journals, feeds the loss scale and
        the divergence budget — the manual flow must not be a silent
        hole in the defense. (On update-on-kvstore the optimizer
        already ran during ``allreduce_grads()``'s push, which carries
        the pre-push guard — nothing is applied here.)"""
        self._init_kvstore()
        self._check_grads()
        scaler = self._active_scaler()
        cfg = self._guard_cfg
        self._optimizer.rescale_grad = self._scale / batch_size
        guarded = scaler is not None or cfg is not None
        # one logical step per update() call — counted here in every
        # combination EXCEPT guarded update-on-kvstore, where the guarded
        # allreduce_grads() push already counted it (the checkpoint()
        # default step rides this counter, so it must track the manual
        # flow too, guarded or not)
        if not (guarded and self._optimizer_applied_on_kv):
            self._step_count += 1
        if not self._optimizer_applied_on_kv and guarded:
            ok, gn, loss_v, gnorm_dev = self._fetch_guard(
                self._grad_datas(first_replica_only=self._kvstore
                                 is not None),
                None)
            # loss_v is non-None only when a PEER rank sent a loss this
            # step (adopted mean) — it must feed the monitor here too or
            # this rank's divergence verdict forks from the senders'
            if not self._note_guard_outcome(ok, gn, scaler, loss_v):
                return
            self._apply_guard_clip(gnorm_dev)
            self._update(ignore_stale_grad)
            if scaler is not None:
                scaler.update_scale(False)
            return
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._optimizer_applied_on_kv:
            return  # weights were updated on the kvstore and pulled back
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            for upd, arr, grad in zip(
                    self._updaters * len(param.list_data()),
                    param.list_data(), param.list_grad()):
                g = grad
                if getattr(param, "_grad_stype", "default") \
                        == "row_sparse":
                    rs = getattr(grad, "_sparse", None)
                    if rs is not None and \
                            not getattr(grad, "_sparse_used", False):
                        g = rs    # touched-rows-only update. The view
                        # stays readable (param.grad()) but is marked
                        # consumed so a step without a fresh backward
                        # doesn't re-apply it (the dense path's stale
                        # grad is the zero buffer).
                        grad._sparse_used = True
                    elif rs is not None:
                        continue  # stale sparse grad: nothing new to apply
                upd(i, g, arr)

    def _handle_divergence(self):
        # optimizer passed as a getter: restore() -> load_states replaces
        # self._optimizer, and the LR backoff must land on the new object
        handle_divergence(
            self._monitor, self._step_count,
            restore_fn=lambda: self.restore(self._guard_cfg.ckpt_root),
            optimizer=lambda: self._optimizer)

    @property
    def skipped_steps(self):
        """Steps skipped on a non-finite gradient so far."""
        return self._skipped_steps

    # -- commit-protocol checkpoint (docs/checkpointing.md) ------------------
    # The sharded trainers own the multi-host story; this is the eager
    # single-process equivalent so divergence rollback (guardrails) and
    # plain crash-consistent training work on the compatibility path too.
    def checkpoint(self, ckpt_dir, step=None, keep_last=None):
        """Stage params + optimizer state under ``<ckpt_dir>/step-N.tmp``
        and publish behind a CRC manifest + rename (resilience.commit).
        ``step`` defaults to the count of completed ``step()`` calls.
        Returns the committed step."""
        self._init_kvstore()
        from ..parallel import _ckpt

        def save_cb(prefix):
            self._save_params_file(f"{prefix}.params")
            self.save_states(f"{prefix}.states")

        step = int(self._step_count if step is None else step)
        return _ckpt.commit_checkpoint(ckpt_dir, step, save_cb,
                                       keep_last=keep_last)

    def restore(self, ckpt_dir, step=None):
        """Restore the newest CRC-valid committed step (corrupt/torn
        candidates journaled as ``ckpt_fallback`` and skipped). Returns
        the restored step."""
        self._init_kvstore()
        from ..parallel import _ckpt

        def load_cb(prefix):
            self._load_params_file(f"{prefix}.params")
            self.load_states(f"{prefix}.states")

        restored = _ckpt.restore_checkpoint(ckpt_dir, load_cb, step=step)
        self._step_count = restored
        if self._kvstore is not None and self._optimizer_applied_on_kv:
            # the kvstore holds the MASTER weights on this path (push
            # applies the optimizer to kv._store, pull copies store →
            # params): without a writeback the next step() would apply
            # grads to the store's un-restored diverged weights and the
            # pull would silently undo the rollback
            for i, param in enumerate(self._params):
                if param.grad_req == "null":
                    continue
                dst = self._kvstore._store.get(str(i))
                if dst is not None:
                    src = param.data(param.list_ctx()[0])
                    dst._rebind(src.as_in_context(dst.ctx)._data)
        return restored

    def _save_params_file(self, fname):
        from .. import ndarray as nd
        nd.save(fname, {p.name: p.data(p.list_ctx()[0])
                        for p in self._params})

    def _load_params_file(self, fname):
        from .. import ndarray as nd
        loaded = nd.load(fname)
        for p in self._params:
            if p.name not in loaded:
                raise MXNetError(f"checkpoint {fname} is missing "
                                 f"parameter {p.name!r}")
            # set_data (not a raw _rebind): per-context placement so
            # multi-replica trainers don't end up with every replica
            # aliasing one load-device array, and the shape check
            # rejects a wrong-shaped checkpoint entry here instead of
            # as an opaque mid-step error
            p.set_data(loaded[p.name])

    def save_states(self, fname):
        """ref: Trainer.save_states — optimizer/updater state checkpoint."""
        self._init_kvstore()
        if self._optimizer_applied_on_kv:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            from ..resilience.atomic import atomic_write
            with atomic_write(fname, "wb") as f:
                f.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        self._init_kvstore()
        if self._optimizer_applied_on_kv:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                self._updaters[0].set_states(f.read())
            self._optimizer = self._updaters[0].optimizer

"""Gluon basic layers (ref: python/mxnet/gluon/nn/basic_layers.py).

Each layer is a thin HybridBlock over one registered operator, so the same
definition runs eagerly (mx.nd) and inside the jitted program produced by
``hybridize()``.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ..block import Block, HybridBlock

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "LayerNorm", "GroupNorm", "InstanceNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "GELU", "Swish", "SyncBatchNorm"]


class Sequential(Block):
    """Stack of Blocks run in order (ref: nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix, params=self._params)
            net._empty_prefix = True
            for layer in layers[key]:
                net.add(layer)
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks, hybridizable as one program
    (ref: nn.HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def hybrid_forward(self, F, x):  # pragma: no cover - forward overrides
        raise NotImplementedError

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix, params=self._params)
            net._empty_prefix = True
            for layer in layers[key]:
                net.add(layer)
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


# activations the fused matmul-epilogue kernel handles (docs/pallas.md):
# Dense routes these through ONE bias+act(+dropout) pass over the matmul
# output instead of separate FullyConnected-bias / Activation ops. gelu
# is epilogue-only (the plain Activation op has no gelu mode).
_EPILOGUE_ACTS = ("relu", "tanh", "sigmoid", "gelu")


class Dense(HybridBlock):
    """y = act(x W^T + b) (ref: nn.Dense → FullyConnected op).

    With ``activation`` in relu/tanh/sigmoid/gelu and a bias, the bias +
    activation (+ ``epilogue_dropout``) run as one fused epilogue over
    the matmul output through the guarded ``mxnet_tpu.pallas`` tier —
    one VMEM pass on TPU, the parity-gated XLA reference elsewhere.
    ``epilogue_dropout`` folds an inverted dropout (training only) into
    the same pass — the dropout-in-epilogue lever from
    docs/roadmap.md items 3-4."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, epilogue_dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        self._epilogue_dropout = float(epilogue_dropout)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    dtype=dtype, allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight._set_shape((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        fuse = bias is not None and (
            self._activation in _EPILOGUE_ACTS
            or (self._activation is None and self._epilogue_dropout > 0))
        if fuse:
            out = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
            return F.contrib.matmul_epilogue(
                out, bias, act_type=self._activation or "identity",
                p=self._epilogue_dropout)
        if bias is None:
            out = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                                   no_bias=False, flatten=self._flatten)
        if self._activation == "gelu":
            # gelu lives on the LeakyReLU op, not Activation (bias-less
            # Dense can't take the fused-epilogue path above)
            out = F.LeakyReLU(out, act_type="gelu")
        elif self._activation is not None:
            out = F.Activation(out, act_type=self._activation)
        if self._epilogue_dropout > 0:
            out = F.Dropout(out, p=self._epilogue_dropout)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape else None} -> {self._units}, "
                f"{self._activation})")


class Dropout(HybridBlock):
    """Inverted dropout (ref: nn.Dropout)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with running stats (ref: nn.BatchNorm).

    Running-stat update is functional: the op returns batch mean/var and the
    layer folds them into the aux parameters; under ``hybridize()`` the
    updated stats become extra outputs of the jitted program (see
    gluon/block.py docstring)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 activation=None, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        # activation fused into the normalize pass (docs/pallas.md):
        # scale*x+offset and the activation run as one conv-epilogue
        # kernel pass on TPU; no extra params, so checkpoints are
        # interchangeable with a BatchNorm + Activation pair
        self._activation = activation
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x):
        channels = x.shape[self._axis]
        for param in (self.gamma, self.beta, self.running_mean,
                      self.running_var):
            param._set_shape((channels,))

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd
        extra = {}
        if self._activation is not None:
            extra["act_type"] = self._activation
        out, mean, var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale, axis=self._axis,
            use_global_stats=self._use_global_stats, **extra)
        if autograd.is_training() and not self._use_global_stats:
            import jax.numpy as jnp
            m = self._momentum
            # cold-start: stats exactly at init (mean 0, var 1) adopt the
            # first batch's statistics outright instead of momentum-mixing
            # with the arbitrary init — so the op's running-mean moment
            # shift (ops/nn.py _batch_norm) is near the true mean from
            # step 2 on even for |mean|>>std inputs (torch's
            # num_batches_tracked warmup has the same effect). Tiny,
            # per-channel-vector-only compute; data-dependent via where
            # so it traces into jitted steps.
            cold = jnp.logical_and(jnp.all(running_mean._data == 0),
                                   jnp.all(running_var._data == 1))
            new_mean = jnp.where(
                cold, mean._data,
                running_mean._data * m + mean._data * (1 - m))
            # At COLD start the op's reported batch var can be destroyed
            # by cancellation (the zero-init shift; ops/nn.py) — and
            # adopting it outright would poison eval for many steps. The
            # cancellation test mean² >> var is only meaningful while the
            # shift is the init value, so it gates the COLD adoption
            # alone: suspicious channels keep the init var for one step
            # (the shift warms at step 2 via new_mean, after which the
            # op's var is sound and momentum-mixes normally — gating warm
            # steps on this data property would freeze running_var
            # forever for any |mean|/std > 64 channel).
            susp_cold = jnp.logical_and(
                cold,
                jnp.square(mean._data) > 4096.0 * jnp.maximum(
                    var._data.astype(mean._data.dtype), 1e-30))
            new_var = jnp.where(
                susp_cold, running_var._data,
                jnp.where(cold, var._data,
                          running_var._data * m + var._data * (1 - m)))
            running_mean._rebind(
                new_mean.astype(running_mean._data.dtype))
            running_var._rebind(new_var.astype(running_var._data.dtype))
        return out

    def __repr__(self):
        return (f"BatchNorm(axis={self._axis}, momentum={self._momentum}, "
                f"eps={self._epsilon}, in_channels="
                f"{self.gamma.shape[0] if self.gamma.shape else None})")


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (ref: contrib.nn.SyncBatchNorm). On TPU the
    mesh-wide statistics come from ``psum`` inside the sharded program when
    run under mxnet_tpu.parallel; single-process semantics equal BatchNorm."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         in_channels=in_channels, **kwargs)


class LayerNorm(HybridBlock):
    """ref: nn.LayerNorm."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x):
        channels = x.shape[self._axis]
        self.gamma._set_shape((channels,))
        self.beta._set_shape((channels,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    """ref: nn.GroupNorm."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x):
        self.gamma._set_shape((x.shape[1],))
        self.beta._set_shape((x.shape[1],))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class InstanceNorm(HybridBlock):
    """ref: nn.InstanceNorm."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)

    def infer_shape(self, x):
        self.gamma._set_shape((x.shape[1],))
        self.beta._set_shape((x.shape[1],))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class Embedding(HybridBlock):
    """Lookup table (ref: nn.Embedding)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    """ref: nn.Flatten."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap an arbitrary function as a Block (ref: nn.Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            if not hasattr(F, function):
                raise MXNetError(f"nd has no function {function!r}")
            self._func = getattr(F, function)
            self._name = function
        else:
            self._func = function
            self._name = getattr(function, "__name__", "lambda")

    def forward(self, *args):
        return self._func(*args)

    def __repr__(self):
        return f"Lambda({self._name})"


class HybridLambda(HybridBlock):
    """ref: nn.HybridLambda."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func = lambda F, *args: getattr(F, function)(*args)
            self._name = function
        else:
            self._func = function
            self._name = getattr(function, "__name__", "lambda")

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"HybridLambda({self._name})"


class Activation(HybridBlock):
    """ref: nn.Activation."""

    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def _alias(self):
        return getattr(self, "_act_type", "activation")

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    """ref: nn.LeakyReLU."""

    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"LeakyReLU({self._alpha})"


class PReLU(HybridBlock):
    """ref: nn.PReLU — learnable slope."""

    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer
        if alpha_initializer is None:
            alpha_initializer = initializer.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    """ref: nn.ELU."""

    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """ref: nn.SELU."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    """ref: nn.GELU."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    """ref: nn.Swish."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)

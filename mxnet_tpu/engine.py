"""Execution-engine facade.

The reference's ThreadedEngine (ref: include/mxnet/engine.h Engine;
src/engine/threaded_engine_perdevice.cc) is an async var-dependency scheduler:
ops are pushed with read/write var sets and run on worker threads + device
streams when dependencies resolve. On TPU that machinery lives *inside* the
runtime — JAX/PjRt dispatch is already asynchronous and dataflow-ordered, so
this module is a thin facade that preserves the reference's observable
behavior:

- ops return to Python before compute finishes (native to JAX);
- ``waitall()`` / per-array ``wait_to_read()`` barriers;
- ``MXNET_ENGINE_TYPE=NaiveEngine`` serializes execution for debugging
  (ref: src/engine/naive_engine.cc), here by blocking after every op —
  the race-debugging affordance SURVEY §5.2 calls out;
- ``bulk`` scoping (ref: Engine::set_bulk_size) becomes a no-op hint, since
  XLA fuses inside jit.
"""
from __future__ import annotations

import contextlib
import threading

import jax

from .base import getenv

__all__ = ["is_naive", "set_engine_type", "on_op_done", "waitall", "bulk"]

_state = threading.local()
_ENGINE_TYPE = getenv("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")

_live_arrays = []  # weak set of pending outputs not needed: JAX tracks deps


def set_engine_type(name: str):
    """Switch engine mode at runtime ('NaiveEngine' == synchronous)."""
    global _ENGINE_TYPE
    _ENGINE_TYPE = name


def is_naive() -> bool:
    return _ENGINE_TYPE == "NaiveEngine"


def on_op_done(out_data):
    """Called by the dispatch layer after every op; in NaiveEngine mode this
    blocks, making failures deterministic and ordered (the reference's
    debugging mode)."""
    if is_naive() and not isinstance(out_data, jax.core.Tracer):
        jax.block_until_ready(out_data)
    return out_data


def waitall():
    """Barrier on all outstanding async work
    (ref: Engine::WaitForAll / mx.nd.waitall)."""
    from .diagnostics import guard
    from .diagnostics.journal import get_journal
    try:
        for dev in guard.devices():
            # synchronize per device; effective barrier is blocking on all
            # live arrays, which JAX exposes per-array. A cheap global barrier:
            jax.device_put(0, dev).block_until_ready()
    except Exception as exc:
        # a torn-down or unreachable backend (or a partially-finalized
        # jax during interpreter shutdown) must not crash a barrier on a
        # teardown path — but the failure must leave a breadcrumb
        # (G6: journaled, not silently swallowed)
        try:
            get_journal().event("waitall_failed", error=type(exc).__name__,
                                detail=str(exc)[:300])
        except Exception:
            pass    # journal unusable at teardown (sink gone, stderr
            # finalized): a barrier must never crash shutdown


@contextlib.contextmanager
def bulk(size: int = 15):
    """ref: mx.engine.bulk — batches engine ops to cut dispatch overhead.
    XLA fusion inside jit supersedes it; kept for script compatibility."""
    yield

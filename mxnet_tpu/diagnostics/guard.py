"""Device-dial guard — the ONE sanctioned path to JAX backend init.

Anything that touches the backend (``jax.devices()``, first array
creation, profiler start) can hang *indefinitely* when the TPU tunnel is
wedged (docs/perf_notes.md round-4 pitfall; the proven cause of two
consecutive information-free ``rc:124`` driver gates, VERDICT r5). The
reference never dials devices at library load — per-device resources are
built lazily by ``src/resource.cc``'s ResourceManager — and this module
is the TPU-native equivalent choke point:

- ``probe_backend()`` dials ``jax.devices()`` in a THROWAWAY subprocess
  under a hard deadline, with retries + backoff; a wedged tunnel costs a
  bounded wait and a structured :class:`DeviceUnreachable`, never a hang
  of the calling process.
- ``ensure_backend()`` is the in-process dial: journal breadcrumbs
  bracket the touch and a deadline timer dumps all-thread tracebacks if
  the dial stalls, so even an unkillable C-level hang leaves an
  attributable artifact. Optionally runs ``probe_backend()`` first so
  the caller finds out the tunnel is wedged without wedging itself.

Import-light by contract: jax is imported lazily inside functions, so
``import mxnet_tpu.diagnostics`` can run in processes that must never
risk a backend touch.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

from .journal import get_journal

__all__ = ["DeviceUnreachable", "probe_backend", "ensure_backend",
           "backend_dialed", "devices", "probe_deadline_s"]

DEFAULT_PROBE_DEADLINE_S = 150.0   # first TPU compile dial can take ~40s
DEFAULT_BACKOFF_S = (0.0,)         # one attempt unless the caller opts in

_PROBE_CODE = (
    "import json, sys\n"
    "import jax\n"
    "ds = jax.devices()\n"
    "print(json.dumps({'platform': ds[0].platform, 'n': len(ds),\n"
    "                  'kinds': sorted({d.device_kind for d in ds}),\n"
    "                  'process_index': jax.process_index(),\n"
    "                  'process_count': jax.process_count()}))\n"
)


def probe_deadline_s(deadline_s=None) -> float:
    """Resolve the probe deadline: explicit arg, else
    ``MXNET_TPU_PROBE_DEADLINE`` (seconds), else 150."""
    if deadline_s is not None:
        return float(deadline_s)
    env = os.environ.get("MXNET_TPU_PROBE_DEADLINE")
    try:
        return float(env) if env else DEFAULT_PROBE_DEADLINE_S
    except ValueError:
        return DEFAULT_PROBE_DEADLINE_S


class DeviceUnreachable(RuntimeError):
    """The backend did not answer within the deadline. Carries a
    machine-readable record (``to_dict()``) so callers can emit it on
    their one-structured-line artifact contract instead of dying with an
    information-free timeout."""

    def __init__(self, detail: str, deadline_s: float, attempts: int,
                 stderr_tail: str = ""):
        super().__init__(detail)
        self.detail = detail
        self.deadline_s = float(deadline_s)
        self.attempts = int(attempts)
        self.stderr_tail = stderr_tail[-500:]

    def to_dict(self) -> dict:
        return {"error": "device_unreachable", "detail": self.detail,
                "deadline_s": self.deadline_s, "attempts": self.attempts,
                "stderr_tail": self.stderr_tail}


def _parse_info_line(stdout: str):
    """Last parseable probe-info line of a probe child's stdout, or None.
    Malformed child output (a library spraying text or JSON-shaped logs
    onto stdout, a truncated write from a dying tunnel) must degrade to
    a structured failure, never an exception or a bogus success
    (ADVICE r5 low, bench.py:81) — so the dict must carry the probe's
    required keys before it counts."""
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                info = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(info, dict) and "platform" in info \
                    and "n" in info:
                return info
    return None


def probe_backend(deadline_s=None, backoff_s=None, env=None,
                  _code=None) -> dict:
    """Dial ``jax.devices()`` in a throwaway subprocess under a hard
    deadline. Returns ``{"platform", "n", "kinds", "process_index",
    "process_count", "probe_s"}`` on success; raises
    :class:`DeviceUnreachable` after all attempts.

    ``backoff_s`` is a tuple of pre-attempt sleeps — its length is the
    attempt count (bench.py uses ``(0, 20, 45)``). Each attempt's outcome
    is journaled, so a driver's stderr tail shows *why*, not just rc.
    """
    deadline_s = probe_deadline_s(deadline_s)
    backoff_s = tuple(backoff_s) if backoff_s is not None else \
        DEFAULT_BACKOFF_S
    code = _code or _PROBE_CODE
    j = get_journal()
    last_err = ""
    for attempt, backoff in enumerate(backoff_s, start=1):
        if backoff:
            time.sleep(backoff)
        t0 = time.perf_counter()
        try:
            out = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True, env=env,
                                 timeout=deadline_s)
        except subprocess.TimeoutExpired:
            last_err = (f"probe attempt {attempt}/{len(backoff_s)} timed "
                        f"out after {deadline_s:g}s")
            j.event("probe_timeout", attempt=attempt,
                    deadline_s=deadline_s)
            continue
        dt = time.perf_counter() - t0
        if out.returncode == 0:
            info = _parse_info_line(out.stdout)
            if info is not None:
                info["probe_s"] = round(dt, 1)
                j.event("probe_ok", attempt=attempt, **info)
                return info
            last_err = (f"probe attempt {attempt}/{len(backoff_s)}: rc=0 "
                        f"but no parseable JSON on stdout")
        else:
            last_err = (f"probe attempt {attempt}/{len(backoff_s)} failed "
                        f"rc={out.returncode}")
        j.event("probe_failed", attempt=attempt, rc=out.returncode,
                stderr_tail=out.stderr.strip()[-300:])
    raise DeviceUnreachable(
        f"jax.devices() did not answer within {deadline_s:g}s in any of "
        f"{len(backoff_s)} attempt(s) (backoffs {backoff_s}s); last: "
        f"{last_err}", deadline_s, len(backoff_s), last_err)


_dial_lock = threading.RLock()
_backend_info: dict | None = None


def backend_dialed() -> bool:
    """True once :func:`ensure_backend` has completed in this process."""
    return _backend_info is not None


def ensure_backend(deadline_s=None, probe_in_subprocess=False,
                   tag=None) -> dict:
    """Initialize (or confirm) the JAX backend through the guarded path.

    - Cached: after the first success this returns immediately, so
      routing hot paths (the RNG global key, profiler start) through it
      costs one dict lookup.
    - ``probe_in_subprocess=True``: run :func:`probe_backend` first — a
      wedged tunnel raises :class:`DeviceUnreachable` from the throwaway
      child instead of wedging THIS process. Use it anywhere a hang is
      worse than a ~2-5s subprocess jax import (driver gates, CLIs).
    - The in-process dial is bracketed by journal breadcrumbs, and a
      deadline timer dumps all-thread faulthandler tracebacks into the
      journal if the dial stalls — an rc:124 artifact then carries
      ``backend_dial`` as the last-known phase plus the hung stack.

    Returns ``{"platform", "n", "dial_s", ...}``.
    """
    global _backend_info
    if _backend_info is not None:
        return _backend_info
    with _dial_lock:
        if _backend_info is not None:
            return _backend_info
        deadline = probe_deadline_s(deadline_s)
        j = get_journal()
        if probe_in_subprocess:
            # init-once dial: serializing every backend toucher behind
            # ONE deadlined probe is this guard's whole contract
            # graftlint: disable=G15 init-once deadlined dial
            probe_backend(deadline_s=deadline)       # raises if unreachable
        stalled = threading.Event()

        def _on_stall():
            stalled.set()
            from .watchdog import _all_thread_tracebacks
            j.event("backend_dial_stall", tag=tag, deadline_s=deadline,
                    tracebacks=_all_thread_tracebacks())

        timer = threading.Timer(deadline, _on_stall)
        timer.daemon = True
        with j.phase("backend_dial"):
            j.event("backend_dial_begin", tag=tag, deadline_s=deadline)
            timer.start()
            t0 = time.perf_counter()
            try:
                import jax
                devs = jax.devices()   # graftlint: disable=G4 this IS the guard
                info = {"platform": devs[0].platform, "n": len(devs),
                        "dial_s": round(time.perf_counter() - t0, 1)}
            finally:
                timer.cancel()
            if stalled.is_set():
                j.event("backend_dial_recovered", tag=tag)
            j.event("backend_ok", tag=tag, **info)
        _backend_info = info
        return info


def devices(local: bool = False):
    """The sanctioned live device list — what static rule G4 points
    every direct ``jax.devices()`` call site at. The first call pays one
    guarded dial (:func:`ensure_backend`: journaled, deadline-timed);
    afterwards the probe is a cached-client lookup. ``local=True``
    returns only this process's addressable devices (in multi-host jobs
    ``jax.devices()`` lists the whole job's)."""
    ensure_backend(tag="device-list")
    import jax
    if local:
        return jax.local_devices()  # graftlint: disable=G4 sanctioned accessor
    return jax.devices()            # graftlint: disable=G4 sanctioned accessor


def _reset_for_tests() -> None:
    global _backend_info
    _backend_info = None

"""``mx.diagnostics`` — runtime health subsystem.

Born from two consecutive driver gates going RED with information-free
``rc:124`` artifacts (VERDICT r5): the runtime could neither refuse a
wedged backend nor say where a process died. Four parts:

- :mod:`.guard` — the ONE sanctioned path to backend init:
  ``ensure_backend()`` / ``probe_backend()`` with hard deadlines and a
  structured :class:`DeviceUnreachable` instead of a hang. Every device
  touch in the package routes through it (the reference's analog:
  resources are built lazily by ``src/resource.cc`` ResourceManager,
  never at library load).
- :mod:`.journal` — append-only JSONL breadcrumbs (phases, timers,
  crashes) with SIGTERM/atexit finalizers, so every killed process
  leaves a last-known phase.
- :mod:`.watchdog` — daemon heartbeats (phase, wall, RSS) + all-thread
  faulthandler dumps when progress stalls.
- ``python -m mxnet_tpu.diagnostics probe|doctor`` — one-command
  environment health report for drivers and CI.

Import-light by contract: importing this package touches neither jax
nor the rest of mxnet_tpu. See docs/diagnostics.md.
"""
from __future__ import annotations

from .guard import (DeviceUnreachable, backend_dialed, devices,
                    ensure_backend, probe_backend)
from .journal import Journal, get_journal, reset_journal
from .watchdog import Watchdog

__all__ = ["DeviceUnreachable", "Journal", "Watchdog", "backend_dialed",
           "devices", "ensure_backend", "get_journal", "probe_backend",
           "reset_journal"]

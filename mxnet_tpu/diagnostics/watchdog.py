"""Watchdog — heartbeat daemon + stall-triggered traceback dumps.

A driver's outer ``timeout`` kill produces an information-free ``rc:124``
unless the process itself leaves breadcrumbs. The watchdog is a daemon
thread that:

1. emits an unbuffered one-line JSON ``heartbeat`` (phase, wall time,
   RSS) every ``interval_s`` — a tail of stderr/the journal file then
   shows the process was alive and *where* it was;
2. when no progress lands for ``stall_s`` (no journal activity and no
   explicit ``beat()``), dumps ``faulthandler`` tracebacks of ALL
   threads into a ``stall`` journal record — captured BEFORE the
   driver's kill, so the artifact pins the hang to a stack, not a guess.

Knobs: ``MXNET_TPU_HEARTBEAT_S`` (default 15), ``MXNET_TPU_STALL_S``
(default 120). Import-light: no jax, no mxnet_tpu.
"""
from __future__ import annotations

import faulthandler
import os
import sys
import tempfile
import threading
import time

from .journal import Journal, get_journal

__all__ = ["Watchdog", "add_stall_callback", "remove_stall_callback"]

DEFAULT_INTERVAL_S = 15.0
DEFAULT_STALL_S = 120.0

# process-wide stall hooks: called (no args) once per stall episode by
# ANY running watchdog, right after its stall record lands.  The slot
# the observability flight recorder registers its wedge dump into —
# a provider slot, not an import, so this module stays import-light
_stall_callbacks: list = []
_stall_cb_lock = threading.Lock()


def add_stall_callback(fn) -> None:
    with _stall_cb_lock:
        if fn not in _stall_callbacks:
            _stall_callbacks.append(fn)


def remove_stall_callback(fn) -> None:
    with _stall_cb_lock:
        try:
            _stall_callbacks.remove(fn)
        except ValueError:
            pass


def _fire_stall_callbacks() -> None:
    with _stall_cb_lock:
        cbs = list(_stall_callbacks)
    for cb in cbs:
        try:
            cb()
        except Exception:
            pass            # a broken dump hook must not kill the watchdog


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v else default
    except ValueError:
        return default


def _rss_mb() -> float:
    """Resident set size in MiB (/proc on Linux, getrusage fallback)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except OSError:
        pass
    try:
        import resource
        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        if sys.platform == "darwin":        # ru_maxrss is bytes on macOS
            rss_kb /= 1024.0
        return round(rss_kb / 1024.0, 1)
    except Exception:
        return -1.0


def _all_thread_tracebacks() -> str:
    """faulthandler dump of every thread, as text (bounded)."""
    try:
        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read()[-8000:]
    except Exception:
        import traceback
        frames = sys._current_frames()
        return "".join(
            f"Thread {tid}:\n" + "".join(traceback.format_stack(fr))
            for tid, fr in frames.items())[-8000:]


class Watchdog:
    """Daemon heartbeat/stall monitor bound to a :class:`Journal`.

    Progress = any non-heartbeat journal record, or an explicit
    ``beat()`` from code that is busy without journaling (a long compile
    loop). One traceback dump per stall episode; a new dump arms again
    once progress resumes.
    """

    def __init__(self, journal: Journal | None = None, interval_s=None,
                 stall_s=None):
        self.journal = journal or get_journal()
        self.interval_s = (float(interval_s) if interval_s is not None
                           else _env_float("MXNET_TPU_HEARTBEAT_S",
                                           DEFAULT_INTERVAL_S))
        self.stall_s = (float(stall_s) if stall_s is not None
                        else _env_float("MXNET_TPU_STALL_S",
                                        DEFAULT_STALL_S))
        self._stop = threading.Event()
        self._thread = None
        self._last_beat = time.monotonic()
        self._dumped = False
        self._t0 = time.monotonic()

    def beat(self) -> None:
        """Record progress without writing a journal record."""
        self._last_beat = time.monotonic()

    def _idle_s(self) -> float:
        last = max(self._last_beat, self.journal.last_activity)
        return time.monotonic() - last

    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="mxnet-tpu-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            idle = self._idle_s()
            self.journal.event("heartbeat", _heartbeat=True,
                               rss_mb=_rss_mb(),
                               wall_s=round(time.monotonic() - self._t0, 1),
                               idle_s=round(idle, 1))
            if idle > self.stall_s:
                if not self._dumped:
                    self._dumped = True
                    # _heartbeat=True: the stall record must not count as
                    # progress, or it would reset its own idle clock
                    self.journal.event(
                        "stall", _heartbeat=True, idle_s=round(idle, 1),
                        stall_threshold_s=self.stall_s,
                        rss_mb=_rss_mb(),
                        tracebacks=_all_thread_tracebacks())
                    # the wedge hook: a registered flight recorder dumps
                    # its span/journal rings while the process can still
                    # be read (the driver's kill comes later)
                    _fire_stall_callbacks()
            else:
                self._dumped = False     # progress resumed: re-arm

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

"""Health CLI: ``python -m mxnet_tpu.diagnostics probe|doctor``.

One-command hermetic environment report for drivers and CI. Both
commands print exactly ONE JSON line on stdout (the artifact contract);
human-readable detail goes to stderr.

``probe``   — dial the backend in a throwaway subprocess under a hard
              deadline (``--deadline``, default MXNET_TPU_PROBE_DEADLINE
              or 150 s). rc 0 = reachable, 1 = unreachable.
``doctor``  — full report: import-time audit (``-X importtime`` in a
              subprocess; the import must complete WITHOUT backend init
              — the round-5 wedge was exactly an import-time dial),
              backend probe, device/mesh shape, relevant env vars.
              rc 0 = healthy, 1 = backend unreachable, 2 = the package
              itself cannot be imported hermetically.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from . import guard

_ENV_KEYS = ("JAX_PLATFORMS", "XLA_FLAGS", "MXNET_TPU_PROBE_DEADLINE",
             "MXNET_TPU_JOURNAL", "MXNET_TPU_HEARTBEAT_S",
             "MXNET_TPU_STALL_S", "MXNET_PRNG_IMPL",
             "MXNET_MATMUL_PRECISION", "MXNET_ENGINE_TYPE",
             "MXTPU_COORD_ADDR", "MXTPU_NUM_PROC", "MXTPU_PROC_ID")


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _env_report() -> dict:
    env = {k: os.environ[k] for k in _ENV_KEYS if k in os.environ}
    hook = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
            if ".axon_site" in p]
    if hook:
        env["pythonpath_site_hook"] = hook
    return env


def _import_audit(deadline_s: float) -> dict:
    """Import the package in a child with ``-X importtime`` and report
    wall time + the slowest modules. The child runs with the CURRENT env
    — if the import dials the backend under a wedged tunnel, the child
    times out and the report says so instead of this process hanging."""
    t0 = time.perf_counter()
    try:
        out = subprocess.run(
            [sys.executable, "-X", "importtime", "-c",
             "import mxnet_tpu"],
            capture_output=True, text=True, timeout=deadline_s)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": "import_timeout",
                "detail": f"import mxnet_tpu exceeded {deadline_s:g}s — "
                          "something dials the backend at import time"}
    dt = time.perf_counter() - t0
    if out.returncode != 0:
        return {"ok": False, "error": "import_failed",
                "rc": out.returncode,
                "stderr_tail": out.stderr.strip()[-500:]}
    total_us, slowest = 0, []
    for line in out.stderr.splitlines():
        # "import time: self [us] | cumulative | imported package"
        parts = line.split("|")
        if len(parts) != 3 or "import time:" not in parts[0]:
            continue
        try:
            self_us = int(parts[0].split(":", 1)[1].strip())
            cum_us = int(parts[1].strip())
        except ValueError:
            continue
        total_us += self_us
        name = parts[2].rstrip()
        # top-level imports only: the name field is " <module>" with two
        # MORE leading spaces per nesting level, so any extra space after
        # the first marks a nested import
        if not name[1:].startswith(" "):
            slowest.append((cum_us, name.strip()))
    slowest.sort(reverse=True)
    return {"ok": True, "import_s": round(dt, 2),
            "import_self_total_s": round(total_us / 1e6, 2),
            "slowest_toplevel": [
                {"module": n, "cumulative_s": round(us / 1e6, 2)}
                for us, n in slowest[:5]]}


def cmd_probe(args) -> int:
    try:
        info = guard.probe_backend(deadline_s=args.deadline,
                                   backoff_s=(0.0,) * args.attempts)
    except guard.DeviceUnreachable as e:
        _emit({"ok": False, **e.to_dict()})
        return 1
    _emit({"ok": True, **info})
    return 0


def _checkpoint_report(root: str) -> dict:
    """Manifest-validity summary of a commit-protocol checkpoint root
    (resilience.commit is stdlib-only: this works even when jax or the
    runtime package is broken)."""
    from ..resilience import commit
    return commit.doctor_report(root)


def _serving_report(path: str) -> dict:
    from ..serving import report
    return report.serving_report(path)


def _guardrails_report(path: str) -> dict:
    from ..elastic import report as elastic_report
    from ..guardrails import report
    out = report.guard_report(path)
    if out.get("ok"):
        # cohort events ride the same journal: rank losses, resizes,
        # resharded restores, and their trace linkage (docs/elastic.md)
        out["elastic"] = elastic_report.elastic_report(path)
    return out


def _trace_report(path: str) -> dict:
    from ..observability import report
    return report.trace_report(path)


def _metrics_report(path: str) -> dict:
    from ..observability import report
    return report.metrics_report(path)


def _timeline_report(run_dir: str) -> dict:
    from ..observability import aggregate
    return aggregate.timeline_report(run_dir)


def _aot_report(dirpath: str) -> dict:
    # imported directly (not via the serving package's heavy siblings):
    # aot_report is stdlib-only, so the audit runs while jax is wedged
    from ..serving import aot_report
    return aot_report.aot_report(dirpath)


def _lint_report(root: str) -> dict:
    from ..analysis import report
    return report.lint_report(root)


def _summ_checkpoint(ck) -> str:
    if ck.get("newest_step") is None:
        return f"checkpoint root {ck['root']}: no committed steps"
    if ck.get("newest_valid"):
        return (f"checkpoint OK: step {ck['newest_step']} manifest + "
                "CRCs valid")
    return (f"checkpoint step {ck['newest_step']} INVALID "
            f"({ck.get('newest_error')}); restorable: "
            f"{ck.get('restorable_step')}")


def _summ_serving(sv) -> str:
    base = (f"serving: {sv['served']} served in {sv['batches']} batches, "
            f"shed-rate {sv['shed_rate']}, cache hit-rate "
            f"{sv['cache_hit_rate']} ({sv['compiles']} compiles), "
            f"{sv['deadline_miss_total']} deadline misses, "
            f"{len(sv['reloads'])} reloads")
    rt = sv.get("router")
    if rt:
        base += (f"; pool: {len(rt['replicas_lost'])} replicas lost, "
                 f"{rt['restarts']} restarts, "
                 f"{len(rt['readmitted'])} re-admitted, "
                 f"{rt['retries']} retries, {rt['hedges']} hedges, "
                 f"{len(rt['breaker_transitions'])} breaker transitions")
    tn = sv.get("tenants")
    if tn:
        # a tenant is "quarantined" per its trail's LAST transition — a
        # sticky re-admitted flag would hide a tenant that re-quarantined
        # after an earlier successful probe
        quarantined = sorted(
            t for t, row in tn.items()
            if row["quarantine_trail"]
            and row["quarantine_trail"][-1]["to"] == "quarantined")
        readmitted = sorted(t for t, row in tn.items()
                            if row["readmitted"])
        trail = sum(len(row["quarantine_trail"]) for row in tn.values())
        pages = sum(row["page_ins"] for row in tn.values())
        base += (f"; fleet: {len(tn)} tenants, {trail} quarantine "
                 f"transitions (quarantined: {quarantined or 'none'}, "
                 f"re-admitted: {readmitted or 'none'}), "
                 f"{pages} page-ins")
    dp = sv.get("deploy")
    if dp:
        last = dp.get("last") or {}
        base += (f"; deploy: step {last.get('from_step')}"
                 f"->{last.get('to_step')} {last.get('result', '?')}"
                 + (f" ({last['reason']})" if last.get("reason") else "")
                 + f", {dp['gate_evals']} gate evals "
                 f"({dp['gate_breaches']} breaches), "
                 f"{dp['mirror_mismatches']} parity mismatches, "
                 f"{dp['rollbacks']} rollbacks")
    return base


def _summ_guardrails(gr) -> str:
    base = (f"guardrails: {gr['skipped_steps']} skipped steps (worst run "
            f"{gr['worst_consecutive_skips']}), {gr['loss_spikes']} loss "
            f"spikes, {len(gr['rollbacks'])} rollbacks, "
            f"{len(gr['diverged_errors'])} diverged")
    el = gr.get("elastic")
    if el and el.get("ok") and any(el["counts"].values()):
        last = el.get("last_resize") or {}
        base += (f"; elastic: {el['counts']['rank_lost']} rank losses, "
                 f"{el['counts']['cohort_resize']} resizes"
                 + (f" (last -> {last.get('members')})"
                    if last else "")
                 + f", {el['counts']['reshard_restore']} reshard "
                   f"restores ({el['correlated_recoveries']} correlated)")
    return base


def _summ_trace(tr) -> str:
    top = ", ".join(f"{s['name']}={s['dur_s']}s" for s in tr["slowest"][:3])
    drops = tr.get("ring_drops", 0)
    return (f"trace: {tr['spans']} spans in {tr['traces']} traces"
            + (f", {drops} ring drops (raise MXNET_TPU_TRACE_RING)"
               if drops else "")
            + f"; slowest: {top or 'n/a'}")


def _summ_timeline(tl) -> str:
    cp = tl.get("critical_path") or {}
    flights = tl.get("flight_dumps") or []
    base = (f"timeline: {len(tl['processes'])} processes "
            f"({tl['traced_processes']} traced) in {tl['path']}"
            + (f"; flight dumps: {', '.join(flights)}" if flights
               else ""))
    if cp.get("ok"):
        chain = " -> ".join(
            f"{s['name']}@{s['proc']}" for s in cp["steps"][:6])
        base += (f"; trace {cp['trace_id']}: {cp['wall_ms']}ms across "
                 f"{len(cp['processes'])} processes: {chain}")
    return base


def _summ_aot(ar) -> str:
    envs = len(ar.get("envelopes") or {})
    return (f"aot-cache: {ar['entries']} entries, {ar['bytes']} bytes, "
            f"{envs} envelope version(s), {ar['stale']} stale, "
            f"{ar['corrupt_total']} corrupt"
            + (f" ({ar['corrupt']})" if ar["corrupt"] else ""))


def _summ_metrics(mt) -> str:
    return (f"metrics: {mt['families']} families, "
            f"{int(mt.get('compiles_total', 0))} compiles")


def _summ_lint(lt) -> str:
    rules = ", ".join(f"{k}={v}" for k, v in sorted(lt["rules"].items()))
    cache = lt.get("cache") or {}
    hr = cache.get("hit_rate")
    return (f"lint: {lt['files']} files in {lt['wall_s']}s, "
            f"{lt['new']} new / {lt['baselined']} baselined"
            + (f" ({rules})" if rules else "")
            + f"; summary-cache hit-rate "
              f"{'n/a' if hr is None else hr}")


def _tuned_report(path) -> dict:
    from ..autotune import table
    return table.audit_table(path)


def _chaos_report(dirpath) -> dict:
    from ..chaos import report
    return report.chaos_report(dirpath)


def _summ_chaos(cr) -> str:
    from ..chaos import report
    return report.summarize(cr)


def _summ_tuned(tt) -> str:
    knobs = tt.get("knobs") or {}
    env = tt.get("envelope") or {}
    shown = ", ".join(f"{k}={v}" for k, v in sorted(knobs.items())[:4])
    more = len(knobs) - 4
    return (f"tuned: {tt['format']} crc={tt['crc32']} "
            f"[{env.get('platform')}/{env.get('device_kind')}/"
            f"jax {env.get('jax')}], {tt.get('trials')} trials; "
            f"{shown}" + (f" (+{more} more)" if more > 0 else ""))


# One row per report surface: adding a reporter means adding one row
# here, not editing three code paths (argument registration, report
# assembly, and the stderr summary all iterate this table).
# (key, flag, env default, metavar, help, load, summarize)
_REPORT_TABLE = (
    ("checkpoint", "--ckpt-dir", "MXNET_TPU_CKPT_DIR", "DIR",
     "commit-protocol checkpoint root: report the latest step's manifest "
     "validity and the newest restorable step (default MXNET_TPU_CKPT_DIR)",
     _checkpoint_report, _summ_checkpoint),
    ("serving", "--serving-journal", None, "PATH",
     "JSONL journal from a serving run (MXNET_TPU_JOURNAL=<file>): "
     "summarize the last run's shed-rate, compile-cache hit-rate, and "
     "deadline-miss count (docs/serving.md)",
     _serving_report, _summ_serving),
    ("guardrails", "--journal", None, "PATH",
     "JSONL journal from a training run (MXNET_TPU_JOURNAL=<file>): "
     "summarize anomaly guardrail records - nonfinite_grad skips, loss "
     "spikes, divergence rollbacks (docs/guardrails.md)",
     _guardrails_report, _summ_guardrails),
    ("trace", "--trace", None, "PATH",
     "JSONL journal from a traced run (MXNET_TPU_TRACE=journal): "
     "summarize span records - counts, per-name durations, slowest "
     "spans (docs/observability.md)",
     _trace_report, _summ_trace),
    ("metrics", "--metrics", None, "PATH",
     "metrics snapshot JSON (a BENCH artifact or a raw "
     "observability.snapshot() dump): summarize compile counts/times "
     "and step-phase percentiles (docs/observability.md)",
     _metrics_report, _summ_metrics),
    ("timeline", "--timeline", "MXNET_TPU_TRACE_DIR", "DIR",
     "pod run directory of per-process journals + flight dumps "
     "(MXNET_TPU_TRACE_DIR during the run): assemble the cross-process "
     "critical path of the slowest routed request — including any "
     "SIGKILLed replica's flight-recorder tail (docs/observability.md)",
     _timeline_report, _summ_timeline),
    ("aot", "--aot-dir", "MXNET_TPU_AOT_CACHE_DIR", "DIR",
     "persistent AOT executable-cache root: audit entry/byte counts, "
     "envelope versions, stale and corrupt entries — CRC-validated "
     "without deserializing anything (docs/serving.md AOT cache)",
     _aot_report, _summ_aot),
    ("lint", "--lint", None, "DIR",
     "repo checkout root: run graftlint (all tiers incl. the "
     "interprocedural G15-G19) and summarize per-rule finding counts "
     "and the summary-cache hit rate (docs/static_analysis.md)",
     _lint_report, _summ_lint),
    ("tuned", "--tuned", "MXNET_TPU_TUNED_TABLE", "PATH",
     "autotuner tuned-table file: validate format/CRC/schema and report "
     "its envelope, trial provenance refs, and per-knob values — "
     "stdlib-only, nothing is applied and no backend is dialed "
     "(docs/autotune.md)",
     _tuned_report, _summ_tuned),
    ("chaos", "--chaos", "MXNET_TPU_CHAOS_DIR", "DIR",
     "directory of chaos-campaign artifacts (CHAOS_rNN.json from "
     "python -m mxnet_tpu.chaos run): summarize campaigns, failed "
     "invariants, and shrunk reproducers — stdlib-only, nothing is "
     "executed (docs/chaos.md)",
     _chaos_report, _summ_chaos),
)


def cmd_doctor(args) -> int:
    deadline = guard.probe_deadline_s(args.deadline)
    report = {"python": sys.version.split()[0],
              "pid": os.getpid(),
              "env": _env_report()}
    for key, flag, _env, _mv, _help, load, _summ in _REPORT_TABLE:
        value = getattr(args, flag.lstrip("-").replace("-", "_"))
        if value:
            report[key] = load(value)
    print(f"doctor: import audit (deadline {deadline:g}s) ...",
          file=sys.stderr)
    report["import_audit"] = _import_audit(deadline)
    print(f"doctor: backend probe (deadline {deadline:g}s) ...",
          file=sys.stderr)
    try:
        info = guard.probe_backend(deadline_s=deadline)
        report["backend"] = {"ok": True, **info}
        flags = os.environ.get("XLA_FLAGS", "")
        report["mesh"] = {
            "devices": info["n"],
            "platform": info["platform"],
            "processes": info.get("process_count", 1),
            "forced_host_device_count":
                "xla_force_host_platform_device_count" in flags}
    except guard.DeviceUnreachable as e:
        report["backend"] = {"ok": False, **e.to_dict()}
    imp, dev = report["import_audit"]["ok"], report["backend"]["ok"]
    report["healthy"] = bool(imp and dev)
    _emit(report)
    if imp:
        print(f"doctor: import OK in "
              f"{report['import_audit']['import_s']}s", file=sys.stderr)
    else:
        print(f"doctor: IMPORT BROKEN: {report['import_audit']}",
              file=sys.stderr)
    if dev:
        print(f"doctor: backend OK: {report['backend']['n']}x "
              f"{report['backend']['platform']} in "
              f"{report['backend']['probe_s']}s", file=sys.stderr)
    else:
        print("doctor: BACKEND UNREACHABLE: "
              f"{report['backend']['detail']}", file=sys.stderr)
    for key, _flag, _env, _mv, _help, _load, summ in _REPORT_TABLE:
        sec = report.get(key)
        if sec is None:
            continue
        if sec.get("ok") is False:
            print(f"doctor: {key}: {sec.get('error')}", file=sys.stderr)
        else:
            print(f"doctor: {summ(sec)}", file=sys.stderr)
    return 0 if report["healthy"] else (2 if not imp else 1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.diagnostics",
        description="runtime health checks (see docs/diagnostics.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("probe", help="subprocess backend dial under a "
                                     "deadline; ONE JSON line on stdout")
    p.add_argument("--deadline", type=float, default=None,
                   help="seconds per attempt (default "
                        "MXNET_TPU_PROBE_DEADLINE or 150)")
    p.add_argument("--attempts", type=int, default=1)
    p.set_defaults(fn=cmd_probe)
    d = sub.add_parser("doctor", help="hermetic environment report: "
                                      "import audit + probe + env")
    d.add_argument("--deadline", type=float, default=None)
    for _key, flag, env, metavar, help_text, _load, _summ in _REPORT_TABLE:
        d.add_argument(flag, metavar=metavar, help=help_text,
                       default=os.environ.get(env) if env else None)
    d.set_defaults(fn=cmd_doctor)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

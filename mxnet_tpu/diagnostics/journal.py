"""Structured event journal — append-only JSONL breadcrumbs.

Two consecutive driver gates went RED with information-free ``rc:124``
artifacts because nothing in the runtime could say *where* a process
wedged (VERDICT r5 "What's weak" #1/#7). The journal is the fix's spine:
every record is ONE JSON line written unbuffered, so a ``tail`` of a
killed process's stderr (or the configured journal file) always carries a
last-known phase. ``install_handlers()`` adds ``SIGTERM``/``atexit``
finalizers that flush a final breadcrumb before the driver's outer kill
lands.

Record schema (all records)::

    {"ts": <unix s>, "up_s": <s since journal start>, "kind": <str>,
     "phase": <innermost active phase>, ...kind-specific fields}

Kinds emitted by this module: ``phase_enter``/``phase_exit`` (paired,
exit carries ``dur_s``), ``phase`` (linear scripts, ``set_phase``),
``timer`` (scoped, carries ``dur_s``), ``crash`` (exception record),
``heartbeat`` (watchdog), ``stall`` (watchdog, carries thread
tracebacks), ``final`` (SIGTERM/atexit breadcrumb, carries
``last_phase`` + ``reason``).

Sink resolution: ``MXNET_TPU_JOURNAL`` env var — a file path (appended),
``stderr`` (default), or ``off``. The stderr sink is looked up at write
time so pytest capture / stream swaps can't strand a stale handle.

This module must stay import-light: no jax, no mxnet_tpu — it is the one
part of the runtime that must work while everything else is wedged.
"""
from __future__ import annotations

import atexit
import contextlib
import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque

__all__ = ["Journal", "get_journal", "reset_journal",
           "set_trace_ids_provider"]

# always-on bounded ring of recent records (the flight recorder's
# journal half): in-memory appends are cheap enough to keep even with
# the sink off, and a crash dump then always has the last-N breadcrumbs
RECENT_CAP_DEFAULT = 256

# Correlation hook (docs/observability.md): observability.trace registers
# its current_ids() here so every record written inside an active span
# carries trace_id/span_id. A provider slot — not an import — because
# this module must stay import-light; with tracing off the provider
# returns {} and records stay bit-identical to the pre-trace schema.
_trace_ids_provider = None


def set_trace_ids_provider(fn) -> None:
    global _trace_ids_provider
    _trace_ids_provider = fn


class Journal:
    """Append-only JSONL event log with phase tracking and exit handlers."""

    def __init__(self, path: str | None = None):
        if path is None:
            path = os.environ.get("MXNET_TPU_JOURNAL", "stderr")
        self.path = path
        self._fh = None
        self._off = path == "off"
        if path not in ("stderr", "off"):
            self._fh = open(path, "a", buffering=1)
        self._lock = threading.RLock()
        # up_s must survive NTP steps (G11): wall clock only for the ts
        # field, monotonic for the uptime duration
        self._t0_mono = time.monotonic()
        self._phase_stack: list[str] = []
        self._last_phase = "startup"
        # monotonic timestamp of the last non-heartbeat record: the
        # watchdog's notion of "the process is making progress"
        self.last_activity = time.monotonic()
        self._handlers_installed = False
        self._final_cbs: list = []
        self._final_done = False
        self._clean = False
        try:
            cap = int(os.environ.get("MXNET_TPU_JOURNAL_RECENT",
                                     RECENT_CAP_DEFAULT))
        except ValueError:
            cap = RECENT_CAP_DEFAULT
        self._recent: deque = deque(maxlen=max(cap, 1))
        # sink-write degrade accounting (full/unwritable disk, closed
        # capture stream): drop-and-count, never raise into the caller
        self.write_drops = 0
        self._drops_uncounted = 0
        self._drop_noted = False

    # -- core record writer --------------------------------------------------
    def event(self, kind: str, _heartbeat: bool = False, **fields) -> dict:
        """Write one JSON line, flushed immediately. Returns the record."""
        rec = {"ts": round(time.time(), 3),
               "up_s": round(time.monotonic() - self._t0_mono, 3),
               "kind": kind, "phase": self._last_phase}
        rec.update(fields)
        if _trace_ids_provider is not None:
            try:
                ids = _trace_ids_provider()
            except Exception:
                ids = None
            if ids:
                for k, v in ids.items():
                    rec.setdefault(k, v)
        line = None if self._off else json.dumps(rec, default=str)
        with self._lock:
            # the bounded recent ring is kept even with the sink off —
            # it is the flight recorder's journal half (heartbeats
            # excluded: they carry no postmortem signal and would
            # evict the records that do)
            if not _heartbeat:
                self._recent.append(rec)
            if line is None:
                return rec
            try:
                fh = self._fh if self._fh is not None else sys.stderr
                fh.write(line + "\n")
                fh.flush()
            except (ValueError, OSError):
                # full disk / closed capture stream: the hot path must
                # never pay for telemetry — drop the line and count it
                self._note_write_drop()
            if not _heartbeat:
                self.last_activity = time.monotonic()
        return rec

    def _note_write_drop(self) -> None:
        """One sink write failed (caller holds the lock). The record
        stays in the recent ring — only the durable line is lost — so
        the count goes to ``mxnet_tpu_journal_write_drops_total`` (when
        the metrics registry is already loaded; this module must not
        import it into a wedged process) plus ONE stderr note per sink.
        """
        self.write_drops += 1
        self._drops_uncounted += 1
        mod = sys.modules.get("mxnet_tpu.observability.metrics")
        if mod is not None:
            try:
                mod.default_registry().counter(
                    "mxnet_tpu_journal_write_drops_total",
                    "journal records dropped because the sink write "
                    "failed (full/unwritable disk or closed stream)",
                ).inc(self._drops_uncounted)
                self._drops_uncounted = 0
            except Exception:
                pass             # accounting must never crash the journal
        if not self._drop_noted:
            self._drop_noted = True
            try:
                sys.stderr.write(
                    f"mxnet_tpu: journal sink {self.path!r} unwritable; "
                    "dropping records (see "
                    "mxnet_tpu_journal_write_drops_total)\n")
            except (ValueError, OSError):
                pass             # stderr itself may be the dead sink

    def recent(self) -> list:
        """Snapshot of the bounded recent-records ring (oldest first) —
        the journal tail a flight-recorder dump preserves for a process
        that can no longer be asked (docs/observability.md)."""
        with self._lock:
            return list(self._recent)

    # -- phases --------------------------------------------------------------
    @property
    def last_phase(self) -> str:
        return self._last_phase

    def set_phase(self, name: str) -> None:
        """Linear-script phase marker (no pairing): updates the last-known
        phase and emits one ``phase`` record."""
        self._last_phase = name
        self.event("phase")

    @contextlib.contextmanager
    def phase(self, name: str):
        """Paired phase scope: ``phase_enter`` on entry, ``phase_exit``
        (with ``dur_s``) on exit; exceptions are journaled as ``crash``
        records and re-raised. Nested phases restore the outer phase."""
        with self._lock:
            self._phase_stack.append(name)
            self._last_phase = name
        self.event("phase_enter")
        t0 = time.perf_counter()
        try:
            yield self
        except BaseException as exc:
            self.crash(exc)
            raise
        finally:
            dur = round(time.perf_counter() - t0, 3)
            self.event("phase_exit", dur_s=dur)
            with self._lock:
                if self._phase_stack and self._phase_stack[-1] == name:
                    self._phase_stack.pop()
                self._last_phase = (self._phase_stack[-1]
                                    if self._phase_stack else "after:" + name)

    @contextlib.contextmanager
    def timer(self, name: str):
        """Scoped timer: one ``timer`` record with ``dur_s`` on exit."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.event("timer", name=name,
                       dur_s=round(time.perf_counter() - t0, 3))

    def crash(self, exc: BaseException, **fields) -> dict:
        """Structured crash record: exception type, message, traceback."""
        tb = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))[-4000:]
        return self.event("crash", error=type(exc).__name__,
                          detail=str(exc)[:500], traceback=tb, **fields)

    # -- exit breadcrumbs ----------------------------------------------------
    def mark_clean(self) -> None:
        """Declare this process's run complete: the ``final`` breadcrumb is
        still written on exit, but registered final callbacks (e.g. a
        bench's 'killed' artifact emitter) are suppressed."""
        self._clean = True

    def install_handlers(self, final_cb=None) -> None:
        """Register ``SIGTERM`` + ``atexit`` finalizers that flush a final
        breadcrumb carrying the last-known phase (so a driver ``timeout``
        kill always leaves an attributable artifact).

        ``final_cb`` (optional, callable) runs once at finalization UNLESS
        ``mark_clean()`` was called first — the hook for emitting a
        structured "killed at phase X" artifact on the process's stdout
        contract line. Callbacks from repeat calls accumulate."""
        if final_cb is not None:
            self._final_cbs.append(final_cb)
        if self._handlers_installed:
            return
        self._handlers_installed = True
        atexit.register(self._finalize, "atexit")
        try:                       # signals only bind in the main thread
            prev = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                self._finalize("sigterm")
                if callable(prev):
                    prev(signum, frame)
                elif prev != signal.SIG_IGN:
                    # restore the default disposition and re-deliver so the
                    # exit status still says "terminated by SIGTERM"
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_term)
        except ValueError:
            pass

    def remove_final_cb(self, final_cb) -> None:
        """Unregister a finalizer added via ``install_handlers(final_cb=
        ...)``: the component that registered it shut down cleanly and
        already wrote its own artifact — the exit-time callback would
        only overwrite it (and keep the component reachable forever)."""
        try:
            self._final_cbs.remove(final_cb)
        except ValueError:
            pass

    def _finalize(self, reason: str) -> None:
        if self._final_done:
            return
        self._final_done = True
        self.event("final", reason=reason, last_phase=self._last_phase,
                   clean=self._clean)
        if not self._clean:
            for cb in self._final_cbs:
                try:
                    cb()
                except Exception:
                    pass

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
            self._off = True


_global_lock = threading.Lock()
_global: Journal | None = None


def get_journal() -> Journal:
    """The process-wide journal (sink from ``MXNET_TPU_JOURNAL``)."""
    global _global
    with _global_lock:
        if _global is None:
            # init-once: opening the sink under the lock IS the
            # singleton contract (uncontended after the first call)
            # graftlint: disable=G15 init-once sink open
            _global = Journal()
        return _global


def reset_journal(path: str | None = None) -> Journal:
    """Replace the process-wide journal (tests / long-lived drivers that
    rotate sinks). The old journal's file handle is closed."""
    global _global
    with _global_lock:
        if _global is not None:
            _global.close()
        # sink rotation: close-old/open-new must be atomic vs writers
        # or a record lands in a closed handle
        # graftlint: disable=G15 atomic sink rotation
        _global = Journal(path)
        return _global

"""The TPU fast path: ONE jitted, GSPMD-sharded train step.

The reference's training step is five engine-queued phases — forward,
backward, kvstore push (gradient reduce), pull, fused optimizer update
(SURVEY §3.2/§3.3: CachedOp::Forward, Imperative::Backward,
KVStoreDist::PushImpl via src/kvstore/comm.h CommDevice reduce,
src/operator/optimizer_op.cc fused updates). Overlap between them emerges
from the ThreadedEngine's var-dependency scheduling.

On TPU the idiomatic design compiles the WHOLE region into a single XLA
program over a device mesh:

- the batch is sharded on the ``data`` mesh axis; the loss is a global mean,
  so XLA *derives* the gradient all-reduce (psum over ICI) from sharding
  propagation — no explicit collective calls, and the latency-hiding
  scheduler overlaps it with backward compute (subsuming the reference's
  P3 priority scheduling, src/kvstore/p3store_dist.h);
- parameters can be tensor-parallel sharded by regex rules (PartitionSpec on
  the ``model`` axis) — a capability the reference only approximates with
  hand ``ctx_group`` placement (example/model-parallel/);
- optimizer state lives sharded exactly like its parameter; the update runs
  in the same program with donated buffers (true in-place, like the
  reference's mutating ``sgd_mom_update``);
- learning rate and step count enter as *traced scalars* so LR schedules
  never retrace the program.
"""
from __future__ import annotations

import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import _rng, autograd
from .. import ndarray as nd
from ..base import MXNetError
from ..gluon.block import functional_apply  # noqa: F401  (re-export: the
#   primitive moved to gluon.block so serving/cache.py can share it
#   without importing the parallel package; trainers keep this name)
from ..guardrails import fused as _guard
from ..guardrails.monitor import AnomalyMonitor, GuardConfig
from ..guardrails.trainer_mixin import GuardedTrainerMixin
from ..observability import instrument as _obs
from ..ops import optimizer_op as _ops
from . import _ckpt
from .mesh import current_mesh

__all__ = ["ShardedTrainer", "functional_apply",
           "allreduce_across_processes", "project_spec"]


def project_spec(mesh, spec):
    """A PartitionSpec projected onto ``mesh``: axis names the mesh
    doesn't have degrade to replication on that dim.  A dim sharded over
    SEVERAL axes — ``P(("data", "model"), None)`` — keeps exactly the
    axes the mesh still has.  Shared by the trainer's survivor-mesh
    rebuild and the serving shard planner (serving/shardplan.py)."""
    out = []
    for a in spec:
        if isinstance(a, (tuple, list)):
            kept = tuple(x for x in a if x in mesh.axis_names)
            out.append(kept if len(kept) > 1
                       else (kept[0] if kept else None))
        else:
            out.append(a if a is None or a in mesh.axis_names else None)
    return PartitionSpec(*out)


# ---------------------------------------------------------------------------
# Functional optimizer rules: state init + traced-step update per Optimizer
# class. These reuse the SAME fused update kernels as the eager path
# (ops/optimizer_op.py, ref: src/operator/optimizer_op.cc) but thread the
# step count t as a traced value so Adam bias correction / schedules never
# bake into the compiled program.
# ---------------------------------------------------------------------------

def _lr_at(optimizer, t):
    """The lr a single update at step t sees (scheduler-aware) — ONE
    resolution rule shared by both trainers' step and scanned run_steps
    paths."""
    if optimizer.lr_scheduler is not None:
        return float(optimizer.lr_scheduler(t))
    return float(optimizer.learning_rate)


def _lr_sequence(optimizer, t, num_steps):
    """Host-evaluated per-step lr array for a scanned multi-step program:
    each inner step must see the SAME lr a separate step() call would
    (a frozen first-step lr silently changes warmup/decay math)."""
    return jnp.asarray([_lr_at(optimizer, t + i) for i in range(num_steps)],
                       jnp.float32)


def _zeros_like(w):
    return jnp.zeros(w.shape, w.dtype)


def _state_spec(weight_spec, entry):
    """State entries shard like their weight; scalar entries (Nadam's
    schedule product) are replicated. ONE rule for placement and the jit
    in/out shardings — divergence between those produces opaque XLA
    sharding mismatches."""
    return weight_spec if getattr(entry, "ndim", 0) else PartitionSpec()


def _opt_init_state(opt, w):
    name = type(opt).__name__
    if name in ("SGD", "NAG", "Signum"):
        mom = getattr(opt, "momentum", 0.0)
        return (_zeros_like(w),) if mom != 0.0 else ()
    if name in ("Adam", "AdamW", "LAMB", "FTRL", "AdaDelta", "Nadam"):
        state = (_zeros_like(w), _zeros_like(w))
        if name == "Nadam":
            # Nadam's momentum-schedule running product is carried as a
            # scalar state entry (no closed form over a traced t)
            state = state + (jnp.ones((), jnp.float32),)
        return state
    if name in ("RMSProp", "AdaGrad"):
        return (_zeros_like(w),)
    if name == "DCASGD":
        # a real COPY: weights and states are donated separately — the
        # same underlying buffer in both would be donated twice
        prev = jnp.array(w, copy=True)
        if getattr(opt, "momentum", 0.0) != 0.0:
            return (_zeros_like(w), prev)
        return (prev,)
    if name == "FTML":
        return (_zeros_like(w), _zeros_like(w), _zeros_like(w))
    if name == "SGLD":
        return ()
    raise MXNetError(
        f"ShardedTrainer has no functional rule for optimizer "
        f"{name!r}; use the eager gluon.Trainer for it")


def _opt_apply(opt, w, g, state, lr, t, wd, rescale, clip):
    """One traced parameter update; returns (new_w, new_state)."""
    name = type(opt).__name__
    kw = dict(lr=lr, wd=wd, rescale_grad=rescale, clip_gradient=clip)
    if name in ("SGD", "NAG"):
        if not state:
            return _ops._sgd_update(w, g, **kw), ()
        fn = _ops._sgd_mom_update if name == "SGD" else _ops._nag_mom_update
        w2, m2 = fn(w, g, state[0], momentum=opt.momentum, **kw)
        return w2, (m2,)
    if name == "Adam":
        corr = jnp.sqrt(1 - opt.beta2 ** t) / (1 - opt.beta1 ** t)
        w2, m2, v2 = _ops._adam_update(
            w, g, state[0], state[1], beta1=opt.beta1, beta2=opt.beta2,
            epsilon=opt.epsilon, lr=lr * corr, wd=wd, rescale_grad=rescale,
            clip_gradient=clip)
        return w2, (m2, v2)
    if name == "AdamW":
        corr = jnp.sqrt(1 - opt.beta2 ** t) / (1 - opt.beta1 ** t)
        w2, m2, v2 = _ops._adamw_update(
            w, g, state[0], state[1], beta1=opt.beta1, beta2=opt.beta2,
            epsilon=opt.epsilon, lr=lr * corr, wd=wd, rescale_grad=rescale,
            clip_gradient=clip)
        return w2, (m2, v2)
    if name == "LAMB":
        gp, m2, v2 = _ops._lamb_phase1(
            w, g, state[0], state[1], beta1=opt.beta1, beta2=opt.beta2,
            epsilon=opt.epsilon, t=t, bias_correction=opt.bias_correction,
            wd=wd, rescale_grad=rescale, clip_gradient=clip)
        r1 = jnp.linalg.norm(w.astype(jnp.float32))
        r2 = jnp.linalg.norm(gp)
        w2 = _ops._lamb_phase2(
            w, gp, r1, r2, lr=lr,
            lower_bound=opt.lower_bound if opt.lower_bound else -1.0,
            upper_bound=opt.upper_bound if opt.upper_bound else -1.0)
        return w2, (m2, v2)
    if name == "RMSProp":
        w2, n2 = _ops._rmsprop_update(w, g, state[0], gamma1=opt.gamma1,
                                      epsilon=opt.epsilon, **kw)
        return w2, (n2,)
    if name == "AdaGrad":
        w2, h2 = _ops._adagrad_update(w, g, state[0],
                                      epsilon=opt.float_stable_eps, **kw)
        return w2, (h2,)
    if name == "FTRL":
        w2, z2, n2 = _ops._ftrl_update(w, g, state[0], state[1],
                                       lamda1=opt.lamda1, beta=opt.beta, **kw)
        return w2, (z2, n2)
    if name == "Signum":
        if not state:
            return _ops._signsgd_update(w, g, **kw), ()
        g32 = g.astype(jnp.float32) * rescale
        g32 = jnp.where(clip > 0, jnp.clip(g32, -clip, clip), g32)
        m2 = state[0] * opt.momentum - g32 * (1 - opt.momentum)
        w2 = w * (1 - lr * opt.wd_lh) + jnp.sign(m2) * lr
        return w2.astype(w.dtype), (m2,)

    def _g32():
        gg = g.astype(jnp.float32) * rescale
        gg = jnp.where(clip > 0, jnp.clip(gg, -clip, clip), gg)
        return gg + wd * w.astype(jnp.float32)

    if name == "AdaDelta":
        acc_g, acc_d = state
        gg = _g32()
        acc_g2 = opt.rho * acc_g + (1 - opt.rho) * gg * gg
        delta = jnp.sqrt(acc_d + opt.epsilon) / \
            jnp.sqrt(acc_g2 + opt.epsilon) * gg
        acc_d2 = opt.rho * acc_d + (1 - opt.rho) * delta * delta
        return (w.astype(jnp.float32) - delta).astype(w.dtype), \
            (acc_g2, acc_d2)
    if name == "Nadam":
        # note: the eager reference updates its m_schedule product once
        # per update() CALL (i.e. per parameter per step — an upstream
        # quirk); this functional rule keeps the schedule per-parameter,
        # the form the Nadam paper intends. Trajectories differ at the
        # 1e-4 level over a few steps.
        mean, var, msched = state
        gg = _g32()
        d = opt.schedule_decay
        mom_t = opt.beta1 * (1 - 0.5 * 0.96 ** (t * d))
        mom_t1 = opt.beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * d))
        msched2 = msched * mom_t
        msched_next = msched2 * mom_t1
        m2 = opt.beta1 * mean + (1 - opt.beta1) * gg
        v2 = opt.beta2 * var + (1 - opt.beta2) * gg * gg
        g_p = gg / (1 - msched2)
        m_p = m2 / (1 - msched_next)
        v_p = v2 / (1 - opt.beta2 ** t)
        m_bar = (1 - mom_t) * g_p + mom_t1 * m_p
        w2 = w.astype(jnp.float32) - lr * m_bar / (jnp.sqrt(v_p)
                                                   + opt.epsilon)
        return w2.astype(w.dtype), (m2, v2, msched2)
    if name == "DCASGD":
        gg = g.astype(jnp.float32) * rescale
        gg = jnp.where(clip > 0, jnp.clip(gg, -clip, clip), gg)
        prev = state[-1]
        w32 = w.astype(jnp.float32)
        comp = gg + wd * w32 + opt.lamda * gg * gg * (w32 - prev)
        if len(state) == 1:
            return (w32 - lr * comp).astype(w.dtype), (w32,)
        m2 = opt.momentum * state[0] - lr * comp
        return (w32 + m2).astype(w.dtype), (m2, w32)
    if name == "FTML":
        dst, vst, zst = state
        gg = _g32()
        v2 = opt.beta2 * vst + (1 - opt.beta2) * gg * gg
        d2 = (1 - opt.beta1 ** t) / lr * (
            jnp.sqrt(v2 / (1 - opt.beta2 ** t)) + opt.epsilon)
        sigma = d2 - opt.beta1 * dst
        z2 = opt.beta1 * zst + (1 - opt.beta1) * gg - sigma * \
            w.astype(jnp.float32)
        return (-z2 / d2).astype(w.dtype), (d2, v2, z2)
    raise MXNetError(f"no functional update for {name}")


def _collect_aux_losses(block):
    """Sum of weighted auxiliary losses stashed by routed layers during the
    CURRENT trace (gluon.contrib.nn.MoEFFN sets ``_trace_aux_loss`` +
    ``aux_loss_weight`` each forward — the Switch load-balancing term).
    Read-and-clear, so no tracer outlives its trace. Returns None when the
    model has no such layers."""
    total, found = 0.0, False
    stack, seen = [block], set()
    while stack:
        b = stack.pop()
        if id(b) in seen:
            continue
        seen.add(id(b))
        al = getattr(b, "_trace_aux_loss", None)
        if al is not None:
            b._trace_aux_loss = None
            if getattr(b, "aux_loss_weight", 0.0):
                total = total + b.aux_loss_weight * al
                found = True
        stack.extend(getattr(b, "_children", {}).values())
    return total if found else None


class ShardedTrainer(GuardedTrainerMixin):
    """Gluon-level driver for the single-program SPMD step.

    Drop-in upgrade of ``gluon.Trainer`` for mesh execution::

        mesh = parallel.make_mesh({"data": 4, "model": 2})
        trainer = parallel.ShardedTrainer(net, loss_fn, "sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            mesh=mesh,
            param_rules=[(r".*dense\\d+_weight", PartitionSpec(None, "model"))])
        loss = trainer.step(x, y)          # one fused XLA program

    The reference analog is Trainer.step's allreduce+update flow
    (ref: python/mxnet/gluon/trainer.py _allreduce_grads/_update) — here both
    happen inside the compiled program, overlapped by XLA's scheduler.
    """

    _guard_consumer = "sharded_trainer"

    def __init__(self, block, loss_fn, optimizer, optimizer_params=None,
                 mesh: Mesh = None, param_rules=None, batch_axis=0,
                 donate=True, compute_dtype=None, remat=None,
                 master_dtype=None, guard=None):
        from .. import optimizer as opt_mod
        self._block = block
        self._loss = loss_fn
        optimizer_params = optimizer_params or {}
        self._optimizer = (optimizer if isinstance(optimizer, opt_mod.Optimizer)
                           else opt_mod.create(optimizer, **optimizer_params))
        # compute_dtype="bfloat16": forward/backward in bf16 on the MXU with
        # fp32 master weights — the reference's multi-precision (`mp_*`)
        # scheme (ref: src/operator/optimizer_op.cc mp_sgd_update) fused
        # into the step; the optimizer update stays fp32. When unset, the
        # process-wide AMP dtype applies (contrib.amp.init).
        self._explicit_compute_dtype = compute_dtype is not None
        if compute_dtype is None:
            from ..contrib.amp import amp_dtype
            compute_dtype = amp_dtype()
        self._compute_dtype = (jnp.dtype(compute_dtype)
                               if compute_dtype is not None else None)
        # remat: rematerialization policy for the forward pass — the
        # `jax.checkpoint` HBM↔FLOPs trade (MXNET_BACKWARD_DO_MIRROR is the
        # reference's analog, ref: src/executor/graph_executor.cc mirror
        # path). None keeps XLA's default saved-activation schedule;
        # "full" saves nothing (recompute the whole forward in backward);
        # "dots" saves matmul/conv outputs and recomputes elementwise chains;
        # a callable is passed through as a jax.checkpoint policy.
        if remat in (None, "full"):
            self._remat_policy = remat
        elif remat == "dots":
            self._remat_policy = jax.checkpoint_policies.dots_saveable
        elif remat == "dots_no_batch":
            self._remat_policy = \
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif callable(remat):
            self._remat_policy = remat
        else:
            raise MXNetError(f"unknown remat policy {remat!r}; expected "
                             "None, 'full', 'dots', 'dots_no_batch' or a "
                             "jax.checkpoint policy callable")
        # master_dtype: storage dtype of weights + optimizer state. Default
        # fp32 masters (the reference's multi-precision mp_* scheme);
        # "bfloat16" halves parameter/state HBM traffic at the cost of
        # update precision — the update math itself stays fp32-internal
        # (ops/optimizer_op.py casts per-kernel).
        self._master_dtype = (jnp.dtype(master_dtype)
                              if master_dtype is not None else None)
        if self._compute_dtype is None and self._master_dtype is not None:
            # low-precision storage without a compute dtype would feed
            # bf16 weights to fp32 inputs — compute in the master dtype
            self._compute_dtype = self._master_dtype
        self._mesh = mesh
        self._param_rules = [(re.compile(pat), spec)
                             for pat, spec in (param_rules or [])]
        self._batch_axis = batch_axis
        self._donate = donate
        self._prepared = False
        self._num_update = self._optimizer.begin_num_update
        self._step_fn = None
        self._eval_fn = None
        self._out_treedef = None
        # anomaly guardrails (docs/guardrails.md): the fused flag/norm is
        # computed in-program on EVERY step (the reduction is ~free and
        # keeps the program signature stable); the config only decides
        # what the host does with it. fp16 compute always gets a dynamic
        # loss scaler riding the same flag — the parity the eager
        # Trainer's DynamicLossScaler promises, without its host sync.
        self._guard_cfg = GuardConfig.coerce(guard)
        self._monitor = (AnomalyMonitor(self._guard_cfg,
                                        consumer=self._guard_consumer)
                         if self._guard_cfg is not None else None)
        self._scaler = None
        self._resolve_scaler()
        self._guard_state = None
        self._skipped_offset = 0

    def _resolve_scaler(self):
        """(Re)resolve the compute dtype + fp16 loss scaler from the
        LIVE amp state when ``compute_dtype`` wasn't pinned by the
        caller: ``amp.init("float16")`` after construction retraces the
        step with fp16 casts (``_maybe_invalidate_amp``), so the scaler
        — and with it skip-step + scale halving — must follow the
        program's ACTUAL dtype, not a stale ``__init__`` snapshot
        (PipelinedTrainer._resolve_scaler is the same contract)."""
        if not self._explicit_compute_dtype:
            from ..contrib.amp import amp_dtype
            cdt = amp_dtype()
            self._compute_dtype = (jnp.dtype(cdt) if cdt is not None
                                   else self._master_dtype)
        if self._compute_dtype == jnp.float16:
            if self._scaler is None:
                from ..contrib.amp import DynamicLossScaler
                self._scaler = DynamicLossScaler()
        else:
            self._scaler = None
        self._validate_guard_mode()

    # -- sharding layout -----------------------------------------------------
    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = current_mesh()
        return self._mesh

    def _param_spec(self, param):
        # rules match the flat parameter name AND the structural path
        # ('features.3.weight'). Flat names embed process-global counters
        # (dense0 → dense4 in a second net instance), so a rule written
        # against them silently stops matching in a rebuilt net — e.g. on
        # checkpoint resume; structural paths are instance-independent.
        sname = self._struct_name(param)
        for pat, spec in self._param_rules:
            if pat.match(param.name) or pat.match(sname):
                # project onto the live mesh: an axis the mesh doesn't
                # have degrades to replication on that dim, so ONE rule
                # set serves every cohort shape the elastic driver may
                # build (docs/elastic.md) instead of raising at prepare
                return self._spec_on(self.mesh, spec)
        return PartitionSpec()   # replicated (pure data parallel)

    def _batch_spec(self, ndim):
        spec = [None] * ndim
        if "data" in self.mesh.axis_names:
            spec[self._batch_axis] = "data"
        return PartitionSpec(*spec)

    def _shard(self, data, spec):
        return jax.device_put(data, NamedSharding(self.mesh, spec))

    def _shard_batch_arg(self, b):
        """Batch arg → data-sharded device array. Already-placed jax.Arrays
        pass through (device_put with an identical sharding is a no-op), so
        a prefetching input pipeline avoids re-uploads."""
        data = b._data if isinstance(b, nd.NDArray) else b
        if not isinstance(data, jax.Array):
            data = np.asarray(data)
        return self._shard(data, self._batch_spec(np.ndim(data)))

    # -- setup ---------------------------------------------------------------
    def _prepare(self, args):
        if self._prepared:
            return
        from .mesh import use_mesh
        with use_mesh(self.mesh):   # deferred-init pass may hit mesh ops
            self._block._ensure_ready(tuple(
                a if isinstance(a, nd.NDArray) else nd.array(a)
                for a in args))
        trainable, aux = self._block._param_split()
        self._trainable, self._aux = trainable, aux
        self._tr_specs = [self._param_spec(p) for p in trainable]
        self._aux_specs = [self._param_spec(p) for p in aux]
        # move parameter + aux arrays onto the mesh with their target layout;
        # the NDArray handles now hold globally-sharded jax.Arrays
        mdt = self._master_dtype
        for p, spec in zip(trainable, self._tr_specs):
            w = p._data[0]._data
            if mdt is not None and jnp.issubdtype(w.dtype, jnp.floating):
                w = w.astype(mdt)
            p._data[0]._rebind(self._shard(w, spec))
        for p, spec in zip(aux, self._aux_specs):
            p._data[0]._rebind(self._shard(p._data[0]._data, spec))
        # optimizer state, sharded like its weight (scalar state entries
        # — e.g. Nadam's momentum-schedule product — are replicated)
        self._states = []
        for p, spec in zip(trainable, self._tr_specs):
            state = _opt_init_state(self._optimizer, p._data[0]._data)
            self._states.append(tuple(
                self._shard(s, _state_spec(spec, s)) for s in state))
        # in-program guard counters (total skips, consecutive skips),
        # replicated — carried through every step/scan for free
        self._guard_state = tuple(
            self._shard(s, PartitionSpec())
            for s in _guard.init_guard_state())
        self._prepared = True

    # -- the compiled step ---------------------------------------------------
    def _build_step(self, n_inputs):
        block, loss_block, opt = self._block, self._loss, self._optimizer
        wds = [opt._get_wd(i) for i in range(len(self._trainable))]
        lr_mults = [opt._get_lr(i) / max(opt.learning_rate, 1e-30)
                    for i in range(len(self._trainable))]
        clip = opt.clip_gradient if opt.clip_gradient is not None else -1.0
        guard_clip = (self._guard_cfg.clip_norm
                      if self._guard_cfg is not None else None)

        cdt = self._compute_dtype
        # static at trace time: with no guard AND no fp16 scaler the
        # update applies unconditionally (pre-guardrails behavior) — a
        # silent bitwise skip nobody journals or polls would freeze
        # training invisibly, which is worse than the NaN surfacing
        guarded = self._scaler is not None or self._guard_cfg is not None

        def step(tr, aux, states, gstate, key, lr, t, rescale, lscale,
                 *batch):
            inputs, label = batch[:-1], batch[-1]

            def loss_of(tr_):
                if cdt is not None:
                    tr_ = [w.astype(cdt) if jnp.issubdtype(w.dtype,
                                                           jnp.floating)
                           else w for w in tr_]
                    inputs_c = [i.astype(cdt) if jnp.issubdtype(
                        jnp.asarray(i).dtype, jnp.floating) else i
                        for i in inputs]
                else:
                    inputs_c = inputs
                outs, treedef, aux_new = functional_apply(
                    block, key, tr_, aux, inputs_c, training=True)
                self._out_treedef = treedef
                # loss math in fp32 by default; a loss that does its own
                # fp32-accumulated reductions (amp_safe, e.g. the fused
                # sparse softmax-CE) takes compute-dtype outputs directly —
                # for a [tokens, vocab] MLM head the blanket fp32 cast
                # alone materializes GBs of HBM traffic per step
                if getattr(loss_block, "amp_safe", False):
                    out_nds = [nd.NDArray(o, _skip_device_put=True)
                               for o in outs]
                else:
                    out_nds = [nd.NDArray(
                        o.astype(jnp.float32) if jnp.issubdtype(
                            o.dtype, jnp.floating) else o,
                        _skip_device_put=True) for o in outs]
                label_nd = nd.NDArray(label, _skip_device_put=True)
                with autograd.pause(train_mode=True):
                    loss_nd = loss_block(out_nds[0] if len(out_nds) == 1
                                         else out_nds, label_nd)
                loss_val = jnp.mean(loss_nd._data.astype(jnp.float32))
                aux_pen = _collect_aux_losses(block)
                if aux_pen is not None:     # MoE load-balancing term
                    loss_val = loss_val + jnp.asarray(aux_pen,
                                                      jnp.float32)
                # fp16 loss scaling: the gradient sees the SCALED loss
                # (that is what makes fp16 grads overflow-detectable);
                # the reported loss stays unscaled. lscale is traced, so
                # DynamicLossScaler updates never retrace.
                return loss_val * lscale, (loss_val, outs, aux_new)

            if self._remat_policy is not None:
                loss_of = jax.checkpoint(
                    loss_of,
                    policy=(None if self._remat_policy == "full"
                            else self._remat_policy))
            ((_, (loss_val, outs, aux_new)), grads) = jax.value_and_grad(
                loss_of, has_aux=True)(list(tr))
            aux_new = [a.astype(a0.dtype) for a, a0 in zip(aux_new, aux)]
            # fused guard (docs/guardrails.md): ONE squared-sum reduction
            # over every (scaled) grad doubles as the non-finite flag and
            # the global norm. Grads here are already psum-reduced by
            # GSPMD, so the flag is globally agreed — no rank can branch
            # out of a collective (the skip below is data flow).
            inv = jnp.float32(1.0) / lscale
            finite, gnorm_scaled = _guard.guard_stats(grads, loss_val)
            gnorm = gnorm_scaled * inv
            rescale_all = rescale * inv
            if guard_clip is not None:
                # global-norm clip off the already-computed norm: folded
                # into rescale_grad, zero extra passes over the grads
                rescale_all = rescale_all * _guard.clip_scale(
                    gnorm * rescale, jnp.float32(guard_clip))
            new_tr, new_states = [], []
            for i, (w, g, s) in enumerate(zip(tr, grads, states)):
                w2, s2 = _opt_apply(opt, w, g, s, lr * lr_mults[i], t,
                                    wds[i], rescale_all, clip)
                new_tr.append(w2)
                new_states.append(s2)
            # skip-step semantics: a non-finite step is a bitwise no-op
            # for params, optimizer state AND aux state (BatchNorm
            # running stats) — jnp.where, so it works under jit/pjit/scan
            if guarded:
                new_tr = _guard.select(finite, new_tr, list(tr))
                new_states = _guard.select(finite, new_states,
                                           list(states))
                aux_new = _guard.select(finite, aux_new, list(aux))
                gstate2 = _guard.update_guard_state(gstate, finite)
            else:
                gstate2 = gstate
            return (new_tr, aux_new, new_states, gstate2, loss_val,
                    (finite, gnorm), tuple(outs))

        mesh = self.mesh
        ns = lambda spec: NamedSharding(mesh, spec)
        rep = ns(PartitionSpec())
        in_shardings = (
            [ns(s) for s in self._tr_specs],
            [ns(s) for s in self._aux_specs],
            [tuple(ns(_state_spec(s, e)) for e in st)
             for s, st in zip(self._tr_specs, self._states)],
            (rep, rep),                       # guard state
            rep, rep, rep, rep, rep,
        ) + tuple(jax.tree_util.tree_map(
            lambda _: None, tuple(range(n_inputs + 1))))  # batch: auto
        out_shardings = (
            [ns(s) for s in self._tr_specs],
            [ns(s) for s in self._aux_specs],
            [tuple(ns(_state_spec(s, e)) for e in st)
             for s, st in zip(self._tr_specs, self._states)],
            (rep, rep),                       # guard state
            rep, (rep, rep), None,
        )
        donate = (0, 2) if self._donate else ()
        self._raw_step = step
        self._shardings = (in_shardings, out_shardings, donate)
        return jax.jit(step, in_shardings=in_shardings,
                       out_shardings=out_shardings, donate_argnums=donate)

    def step(self, *batch):
        """Run one fused train step; last positional arg is the label.
        Returns the (replicated) scalar loss as an NDArray."""
        args = batch[:-1]
        self._prepare(args)
        self._maybe_invalidate_amp()
        compiling = self._step_fn is None
        if compiling:
            self._step_fn = self._build_step(len(args))
        self._num_update += 1
        t = self._num_update
        # telemetry (docs/observability.md): phases always feed the
        # step-phase summary (host perf_counter only); spans are live
        # only under MXNET_TPU_TRACE — attrs are host scalars, so the
        # deferred-mode zero-device-read contract is untouched
        with _obs.trace.span("sharded_trainer.step", step=t):
            with _obs.step_phase("sharded_trainer", "data_wait"):
                batch_datas = [self._shard_batch_arg(b) for b in batch]
            self._optimizer.num_update = t
            lr = _lr_at(self._optimizer, t)
            rescale = self._optimizer.rescale_grad
            lscale = (self._scaler.loss_scale
                      if self._scaler is not None else 1.0)
            tr = [p._data[0]._data for p in self._trainable]
            aux = [p._data[0]._data for p in self._aux]
            cshapes = ([list(map(int, np.shape(b))) for b in batch]
                       if compiling else None)
            from .mesh import use_mesh
            # mesh-aware ops (ring attention) trace under use_mesh
            with _obs.step_phase("sharded_trainer", "compiled_step"), \
                    _obs.maybe_compile_span(compiling,
                                            "sharded_trainer.step",
                                            shapes=cshapes), \
                    use_mesh(self.mesh):
                (new_tr, aux_new, new_states, gstate, loss_val,
                 (finite, gnorm), outs) = self._step_fn(
                    tr, aux, self._states, self._guard_state,
                    _rng.next_key(), jnp.float32(lr), jnp.float32(t),
                    jnp.float32(rescale), jnp.float32(lscale),
                    *batch_datas)
            for p, w in zip(self._trainable, new_tr):
                p._data[0]._rebind(w)
            for p, a in zip(self._aux, aux_new):
                p._data[0]._rebind(a)
            self._states = new_states
            self._guard_state = gstate
            self.last_outputs = [nd.NDArray(o, _skip_device_put=True)
                                 for o in outs]
            with _obs.step_phase("sharded_trainer", "guard_fetch"):
                self._after_step(t, loss_val, finite, gnorm)
        return nd.NDArray(loss_val, _skip_device_put=True)

    # -- guard bookkeeping: GuardedTrainerMixin (docs/guardrails.md) ----------
    def _reinit_guard_state(self):
        return tuple(self._shard(s, PartitionSpec())
                     for s in _guard.init_guard_state())

    def _maybe_invalidate_amp(self):
        """Retrace compiled programs when the per-op AMP cast policy
        changes (amp.init with op lists / amp.reset) — a stale program
        would silently keep or miss the casts."""
        from .. import _dispatch
        if getattr(self, "_amp_epoch", None) != _dispatch.amp_epoch():
            self._step_fn = None
            self._eval_fn = None
            self._multi_fns = {}
            self._amp_epoch = _dispatch.amp_epoch()
            # the retraced program's dtype may have changed with it —
            # BEFORE the rebuild reads _compute_dtype/_scaler
            self._resolve_scaler()

    def run_steps(self, *batch, num_steps=8):
        """Run ``num_steps`` train steps as ONE compiled program
        (``lax.scan`` over the step body). Amortizes host-dispatch latency
        — the TPU analog of the reference's engine keeping a deep async
        queue ahead of the Python loop (SURVEY §3.2: "the loop
        synchronizes only at metric.update"). The batch is reused each
        inner step; returns the last step's loss."""
        args = batch[:-1]
        self._prepare(args)
        self._maybe_invalidate_amp()
        if self._step_fn is None:
            self._step_fn = self._build_step(len(args))
        key = f"multi{num_steps}"
        if not hasattr(self, "_multi_fns"):
            self._multi_fns = {}
        compiling = key not in self._multi_fns
        if compiling:
            raw = self._raw_step
            in_sh, out_sh, donate = self._shardings
            rep_sh = out_sh[4]

            def multi(tr, aux, states, gstate, rng, lrs, t, rescale,
                      lscale, *b):
                # lrs: (num_steps,) host-evaluated schedule — each inner
                # step sees the SAME lr a separate step() call would
                def body(carry, i):
                    tr_, aux_, states_, gs_, t_ = carry
                    k = jax.random.fold_in(rng, i)
                    ntr, naux, nst, gs2, loss, (fin, gn), _ = raw(
                        tr_, aux_, states_, gs_, k, lrs[i], t_, rescale,
                        lscale, *b)
                    return (ntr, naux, nst, gs2, t_ + 1.0), (loss, fin, gn)

                (tr, aux, states, gstate, _), (losses, fins, gns) = \
                    jax.lax.scan(body, (tr, aux, states, gstate, t),
                                 jnp.arange(num_steps))
                return tr, aux, states, gstate, losses, fins, gns

            self._multi_fns[key] = jax.jit(
                multi, in_shardings=in_sh,
                out_shardings=out_sh[:4] + (rep_sh, rep_sh, rep_sh),
                donate_argnums=donate)
        t = self._num_update + 1
        self._num_update += num_steps
        with _obs.trace.span("sharded_trainer.run_steps", start_step=t,
                             num_steps=num_steps):
            with _obs.step_phase("sharded_trainer", "data_wait"):
                batch_datas = [self._shard_batch_arg(b) for b in batch]
            self._optimizer.num_update = self._num_update
            lrs = _lr_sequence(self._optimizer, t, num_steps)
            # fp16 note (docs/guardrails.md): the loss scale is one traced
            # input for the WHOLE window — overflow inside a scanned window
            # skips those steps in-program, and the scaler adjusts once per
            # window from the per-step flags below
            lscale = (self._scaler.loss_scale
                      if self._scaler is not None else 1.0)
            tr = [p._data[0]._data for p in self._trainable]
            aux = [p._data[0]._data for p in self._aux]
            cshapes = ([list(map(int, np.shape(b))) for b in batch]
                       if compiling else None)
            from .mesh import use_mesh
            with _obs.step_phase("sharded_trainer", "compiled_step"), \
                    _obs.maybe_compile_span(compiling,
                                            "sharded_trainer.run_steps",
                                            num_steps=num_steps,
                                            shapes=cshapes), \
                    use_mesh(self.mesh):
                (new_tr, aux_new, new_states, gstate, losses, fins,
                 gns) = self._multi_fns[key](
                    tr, aux, self._states, self._guard_state,
                    _rng.next_key(), lrs, jnp.float32(t),
                    jnp.float32(self._optimizer.rescale_grad),
                    jnp.float32(lscale), *batch_datas)
            for p, w in zip(self._trainable, new_tr):
                p._data[0]._rebind(w)
            for p, a in zip(self._aux, aux_new):
                p._data[0]._rebind(a)
            self._states = new_states
            self._guard_state = gstate
            with _obs.step_phase("sharded_trainer", "guard_fetch"):
                self._after_run_steps(t, losses, fins, gns)
        return nd.NDArray(losses[-1], _skip_device_put=True)

    def evaluate(self, *batch):
        """Forward + loss under one compiled program (no update)."""
        args = batch[:-1]
        self._prepare(args)
        self._maybe_invalidate_amp()
        if self._eval_fn is None:
            block, loss_block = self._block, self._loss

            def eval_step(tr, aux, key, *b):
                inputs, label = b[:-1], b[-1]
                outs, _, _ = functional_apply(block, key, tr, aux, inputs,
                                              training=False)
                out_nds = [nd.NDArray(o, _skip_device_put=True) for o in outs]
                label_nd = nd.NDArray(label, _skip_device_put=True)
                with autograd.pause(train_mode=False):
                    loss_nd = loss_block(out_nds[0] if len(out_nds) == 1
                                         else out_nds, label_nd)
                return jnp.mean(loss_nd._data.astype(jnp.float32)), \
                    tuple(outs)
            self._eval_fn = jax.jit(eval_step)
        batch_datas = [self._shard_batch_arg(b) for b in batch]
        tr = [p._data[0]._data for p in self._trainable]
        aux = [p._data[0]._data for p in self._aux]
        loss_val, outs = self._eval_fn(tr, aux, _rng.next_key(),
                                       *batch_datas)
        self.last_outputs = [nd.NDArray(o, _skip_device_put=True)
                             for o in outs]
        return nd.NDArray(loss_val, _skip_device_put=True)

    # -- checkpoint / resume -------------------------------------------------
    # The flagship path's checkpoint story (ref: python/mxnet/gluon/
    # trainer.py save_states/load_states; SURVEY §5.4). Differences forced
    # by the sharded world: optimizer state lives as GSPMD-sharded
    # jax.Arrays (possibly bf16 masters), and in a multi-host run no single
    # process holds every shard. The layout is therefore per-shard-capable:
    # each process writes only the shards it owns (``<fname>.shard<rank>``)
    # plus one rank-0 meta file; a single-process run collapses to one
    # ordinary .params-format file readable by ``nd.load``. Resume is
    # bit-exact: master weights and state are stored in their storage dtype
    # (no fp32 round trip), and the global RNG key is part of the state so
    # dropout masks continue the same stream (tests/test_sharded_checkpoint).

    def prepare(self, *example_args):
        """Materialize sharded params + optimizer state without running a
        step (the resume entry point: prepare, then ``load_checkpoint``)."""
        self._prepare(example_args)

    def _require_prepared(self, what):
        if not self._prepared:
            raise MXNetError(
                f"ShardedTrainer.{what} needs the sharded state: call "
                "prepare(*example_args) or run a step first")

    def _struct_name(self, param):
        """Structural key ('features.0.weight') — instance-independent, so a
        checkpoint loads into a freshly-constructed net whose auto-generated
        name prefixes differ (same convention as Block.save_parameters)."""
        by_id = getattr(self, "_struct_cache", None)
        if by_id is None:
            by_id = {}
            for key, p in self._block._structural_names().items():
                by_id.setdefault(id(p), key)
            self._struct_cache = by_id
        return by_id.get(id(param), param.name)

    def _state_entries(self):
        """name -> placed jax.Array for every optimizer-state leaf."""
        out = {}
        for p, st in zip(self._trainable, self._states):
            for j, s in enumerate(st):
                out[f"state:{self._struct_name(p)}:{j}"] = s
        return out

    def _param_entries(self):
        out = {}
        for p in self._trainable:
            out[f"arg:{self._struct_name(p)}"] = p._data[0]._data
        for p in self._aux:
            out[f"aux:{self._struct_name(p)}"] = p._data[0]._data
        return out

    def _ckpt_meta(self, per_shard):
        meta = {
            "format": _ckpt.CKPT_FORMAT,
            "optimizer": type(self._optimizer).__name__,
            "num_update": int(self._num_update),
            "master_dtype": (str(self._master_dtype)
                             if self._master_dtype is not None else None),
            "state_arity": [len(st) for st in self._states],
            "per_shard": bool(per_shard),
            "shard_files": _ckpt.group().count(),
        }
        meta.update(_ckpt.rng_meta())
        return meta

    # file machinery shared with PipelinedTrainer — see parallel/_ckpt.py
    def _write_entries(self, fname, entries, meta):
        _ckpt.write_entries(fname, entries, meta)

    def _read_meta(self, fname):
        return _ckpt.read_meta(fname)

    def _read_pieces(self, fname, n_files):
        needed = _ckpt.needed_piece_keys(
            {**self._state_entries(), **self._param_entries()})
        return _ckpt.read_pieces(fname, n_files, needed)

    def _place_like(self, name, cur, loaded, pieces):
        return _ckpt.place_like(name, cur, loaded, pieces)

    def save_states(self, fname, per_shard=None):
        """Checkpoint optimizer state + step count + RNG stream.

        ``per_shard=None`` auto-selects: one plain ``.params``-format file
        in single-process runs, per-process shard files in multi-host runs.
        API parity: gluon.Trainer.save_states (ref: python/mxnet/gluon/
        trainer.py:save_states)."""
        self._require_prepared("save_states")
        if per_shard is None:
            per_shard = _ckpt.group().count() > 1
        self._write_entries(fname, self._state_entries(),
                            self._ckpt_meta(per_shard))

    def _check_states_meta(self, meta):
        """Shared contract checks for a ``.states`` meta (layout-locked
        and resharded loads alike): optimizer class, master storage
        dtype, state arity."""
        if meta["optimizer"] != type(self._optimizer).__name__:
            raise MXNetError(
                f"checkpoint was saved with optimizer {meta['optimizer']!r}, "
                f"trainer has {type(self._optimizer).__name__!r}")
        want_mdt = (str(self._master_dtype)
                    if self._master_dtype is not None else None)
        if meta.get("master_dtype") != want_mdt:
            raise MXNetError(
                f"checkpoint was saved with master_dtype="
                f"{meta.get('master_dtype')!r}, trainer has {want_mdt!r} — "
                "resume with the same storage dtype (a cast would change "
                "the training trajectory)")
        if meta["state_arity"] != [len(st) for st in self._states]:
            raise MXNetError("checkpoint state arity mismatch — different "
                             "optimizer config or parameter set")

    def load_states(self, fname):
        """Restore what ``save_states`` wrote. The trainer must be prepared
        with the same architecture, optimizer class, master_dtype and (for
        per-shard files) mesh layout."""
        self._require_prepared("load_states")
        meta, loaded = self._read_meta(fname)
        self._check_states_meta(meta)
        pieces = (self._read_pieces(fname, int(meta.get("shard_files", 1)))
                  if meta["per_shard"] else None)
        new_states = []
        for p, st in zip(self._trainable, self._states):
            new_states.append(tuple(
                self._place_like(f"state:{self._struct_name(p)}:{j}", s,
                                 loaded, pieces)
                for j, s in enumerate(st)))
        self._states = new_states
        self._num_update = int(meta["num_update"])
        self._optimizer.num_update = self._num_update
        _ckpt.restore_rng(meta)

    def save_checkpoint(self, prefix, per_shard=None):
        """Full resumable snapshot: ``<prefix>.params`` (master weights +
        aux state, exact storage dtype) and ``<prefix>.states`` (optimizer
        state, step count, RNG). Ref: mx.model checkpoint pair
        (python/mxnet/model.py save_checkpoint) lifted to sharded state."""
        self._require_prepared("save_checkpoint")
        if per_shard is None:
            per_shard = _ckpt.group().count() > 1
        self._write_entries(f"{prefix}.params", self._param_entries(),
                            self._ckpt_meta(per_shard))
        self.save_states(f"{prefix}.states", per_shard=per_shard)

    def load_checkpoint(self, prefix):
        """Bit-exact resume of ``save_checkpoint`` output onto a prepared
        trainer: training continues as if never interrupted
        (tests/test_sharded_checkpoint.py asserts bitwise equality)."""
        self._require_prepared("load_checkpoint")
        meta, loaded = self._read_meta(f"{prefix}.params")
        pieces = (self._read_pieces(f"{prefix}.params",
                                    int(meta.get("shard_files", 1)))
                  if meta["per_shard"] else None)
        for p in self._trainable:
            p._data[0]._rebind(self._place_like(
                f"arg:{self._struct_name(p)}", p._data[0]._data, loaded,
                pieces))
        for p in self._aux:
            p._data[0]._rebind(self._place_like(
                f"aux:{self._struct_name(p)}", p._data[0]._data, loaded,
                pieces))
        self.load_states(f"{prefix}.states")

    def checkpoint(self, ckpt_dir, step=None, keep_last=None,
                   per_shard=None):
        """Crash-consistent directory checkpoint (the commit protocol,
        docs/checkpointing.md): params + optimizer state staged under
        ``<ckpt_dir>/step-N.tmp/``, committed behind a rank-0 CRC
        manifest + rename, ``latest`` pointer moved, keep-last-k
        retention applied. ``step`` defaults to the trainer's completed
        update count. Returns the committed step."""
        self._require_prepared("checkpoint")
        step = int(self._num_update if step is None else step)
        return _ckpt.commit_checkpoint(
            ckpt_dir, step,
            lambda prefix: self.save_checkpoint(prefix,
                                                per_shard=per_shard),
            keep_last=keep_last)

    def restore(self, ckpt_dir, step=None, latest=True):
        """Resume from the newest *valid* committed step under
        ``ckpt_dir`` (or a pinned ``step``): a corrupt/torn newest
        checkpoint is skipped with a journaled ``ckpt_fallback`` and
        the next-newest intact one restored. The trainer must be
        prepared (same architecture/optimizer/mesh contract as
        ``load_checkpoint``). Returns the restored step."""
        self._require_prepared("restore")
        if step is None and not latest:
            raise MXNetError("restore needs step=N or latest=True")
        return _ckpt.restore_checkpoint(ckpt_dir, self.load_checkpoint,
                                        step=step)

    # -- elastic: survivor-mesh rebuild + resharded restore ------------------
    # (docs/elastic.md). Two lanes after a cohort shape change: rebuild
    # the mesh in place when this process still holds the state, or build
    # a fresh trainer and pull the newest committed checkpoint back in
    # through the topology-free reader.

    # module-level project_spec, kept as a method name because the
    # elastic lanes (and their tests) reach it through the trainer
    _spec_on = staticmethod(project_spec)

    def rebuild_mesh(self, mesh):
        """Re-place parameters, aux buffers, optimizer state and guard
        counters onto ``mesh`` and drop every compiled program (new
        shard counts invalidate the cached executable — the retrace is
        journaled, never silent). The current arrays must still be
        readable by this process: after losing a *remote* rank, build a
        fresh trainer and :meth:`restore_resharded` instead."""
        self._require_prepared("rebuild_mesh")
        from ..diagnostics.journal import get_journal
        old_n = self._mesh.devices.size if self._mesh is not None else 0
        self._tr_specs = [self._spec_on(mesh, s) for s in self._tr_specs]
        self._aux_specs = [self._spec_on(mesh, s) for s in self._aux_specs]
        self._mesh = mesh
        for p, spec in zip(self._trainable, self._tr_specs):
            p._data[0]._rebind(
                self._shard(_ckpt.gather_host(p._data[0]._data), spec))
        for p, spec in zip(self._aux, self._aux_specs):
            p._data[0]._rebind(
                self._shard(_ckpt.gather_host(p._data[0]._data), spec))
        self._states = [
            tuple(self._shard(_ckpt.gather_host(s), _state_spec(spec, s))
                  for s in st)
            for spec, st in zip(self._tr_specs, self._states)]
        self._guard_state = tuple(
            self._shard(_ckpt.gather_host(s), PartitionSpec())
            for s in self._guard_state)
        self._step_fn = None
        self._eval_fn = None
        self._multi_fns = {}
        get_journal().event("elastic_retrace", reason="mesh_rebuild",
                            consumer=self._guard_consumer,
                            old_devices=int(old_n),
                            new_devices=int(mesh.devices.size))

    def load_checkpoint_resharded(self, prefix):
        """Topology-aware twin of :meth:`load_checkpoint`: assemble the
        global tree from however many shard files the SAVING cohort
        wrote (meta's recorded shard set, CRC-verified per piece) and
        re-place it onto THIS trainer's mesh — scale-down and scale-up
        alike. Bit-exact: same storage dtypes, same RNG stream."""
        self._require_prepared("load_checkpoint_resharded")
        from ..elastic import reshard as _reshard
        meta, entries = _reshard.read_global_entries(f"{prefix}.params")
        smeta, sentries = _reshard.read_global_entries(f"{prefix}.states")
        self._check_states_meta(smeta)

        def take(name, cur):
            src = sentries if name.startswith("state:") else entries
            if name not in src:
                raise MXNetError(f"checkpoint is missing entry {name!r}")
            return _reshard.place_global(name, cur, src[name])

        self._place_all(take)
        self._num_update = int(smeta["num_update"])
        self._optimizer.num_update = self._num_update
        _ckpt.restore_rng(smeta)
        _reshard.journal_reshard(prefix, self._num_update, meta,
                                 _ckpt.group().count(),
                                 {**entries, **sentries},
                                 self._guard_consumer)

    def restore_resharded(self, ckpt_dir, step=None):
        """Resume from the newest *valid* committed step under
        ``ckpt_dir`` onto the CURRENT topology, regardless of how many
        ranks wrote it (journaled ``ckpt_fallback`` past corrupt steps,
        ``reshard_restore`` on success). Returns the restored step."""
        self._require_prepared("restore_resharded")
        return _ckpt.restore_checkpoint(
            ckpt_dir, self.load_checkpoint_resharded, step=step)

    def _place_all(self, get):
        """Rebind every leaf — params, aux, optimizer state — through
        ``get(name, current_array)`` (the ONE traversal the resharded
        load and the cohort sync share; names match ``_param_entries``/
        ``_state_entries``)."""
        for p in self._trainable:
            p._data[0]._rebind(get(f"arg:{self._struct_name(p)}",
                                   p._data[0]._data))
        for p in self._aux:
            p._data[0]._rebind(get(f"aux:{self._struct_name(p)}",
                                   p._data[0]._data))
        self._states = [
            tuple(get(f"state:{self._struct_name(p)}:{j}", s)
                  for j, s in enumerate(st))
            for p, st in zip(self._trainable, self._states)]

    def _adopt_host_entries(self, entries):
        """Re-place host arrays over the live tree keeping each leaf's
        current sharding — the elastic driver's cohort sync point.
        Names absent from ``entries`` keep their current value."""
        from ..elastic import reshard as _reshard
        self._place_all(
            lambda name, cur: (_reshard.place_global(name, cur,
                                                     entries[name])
                               if name in entries else cur))

    # -- parity helpers ------------------------------------------------------
    @property
    def num_update(self):
        """Completed optimizer updates (restored by load_checkpoint) —
        the public step counter resume logic should read."""
        return self._num_update

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)


def allreduce_across_processes(arr):
    """Eager sum over worker processes — the kvstore ``dist_sync`` reduce
    (ref: src/kvstore/kvstore_dist.h PushImpl aggregate). Rides DCN via the
    JAX coordination service; identity in single-process runs."""
    import jax
    if jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(arr._data)
    return nd.NDArray(jnp.sum(gathered, axis=0), ctx=arr.ctx)

"""Pipeline parallelism over a ``pipe`` mesh axis (net-new capability:
MXNet 1.x has no pipeline schedule — SURVEY §2.4 #32 marks PP absent; the
reference's closest tool is hand `ctx_group` placement).

Design (TPU-idiomatic SPMD):
- L = v*P layers live on P devices; device d owns layers {d, P+d, ...}
  (params stacked on a leading layer axis, sharded over ``pipe``);
- microbatches stream through a static tick loop; activations hop to the
  next stage with ``lax.ppermute`` (one ICI neighbor hop per tick) and
  wrap around the ring v times — the **interleaved/circular schedule**
  (Megatron-LM's interleaved 1F1B shape): with v virtual stages per
  device the bubble shrinks from GPipe's (P-1)·v layer-times to (P-1),
  i.e. fraction (P-1)/(v·m+P-1);
- ``v=1`` degenerates to plain GPipe;
- heterogeneous ends: optional ``embed_fn`` runs on the injection edge
  (stage 0) and ``head_fn`` on the exit edge (last stage), so a real
  model (embedding → N blocks → head) maps without padding tricks. Both
  are evaluated redundantly on every device (their cost is O(1%) of the
  blocks in a transformer) and selected by device index — predication
  instead of per-device branching, the XLA-friendly choice;
- the whole schedule is differentiable end-to-end: jax transposes the
  ppermute chain, so backward is the reverse pipeline automatically —
  activation stashing falls out of the scan's saved residuals instead of
  hand-rolled 1F1B bookkeeping.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..base import MXNetError

try:
    from jax import shard_map
except ImportError:                      # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["pipeline_apply", "pipeline_schedule_info"]


def pipeline_schedule_info(n_stages, num_microbatches, num_virtual=1):
    """Static schedule accounting: total ticks, busy ticks per device,
    and the bubble fraction (P-1)/(v*m+P-1). One "tick" costs one layer
    application (GPipe packs v layers per tick into each of its m+P-1
    ticks, so its bubble is v*(P-1) layer-times — same formula with the
    tick cost scaled)."""
    p, m, v = int(n_stages), int(num_microbatches), int(num_virtual)
    ticks = v * m + p - 1
    busy = v * m
    return {"ticks": ticks, "busy": busy,
            "bubble_fraction": (p - 1) / ticks}


def pipeline_apply(stage_fn, stage_params, x, mesh: Mesh = None,
                   axis_name="pipe", num_microbatches=None,
                   num_virtual_stages=1, embed_fn=None, embed_params=None,
                   head_fn=None, head_params=None, data_axis=None,
                   params_are_split=False, stage_ctx=False):
    """Run ``x`` through L = num_virtual_stages * P pipeline layers.

    stage_fn(params_l, h) -> h'       same signature for every layer;
        activations must share one shape (they ride one ppermute ring)
    stage_ctx: when True, stage_fn is instead called as
        ``stage_fn(params_l, h, ctx)`` with ``ctx = {"layer": <traced
        int, virtual pass * P + device = the layer index>, "tick":
        <traced int, schedule tick>, "shard": <traced int, data-axis
        shard index; 0 when data_axis is None>}`` INSIDE the scan body.
        Fold all three into any RNG key the stage consumes: (layer,
        tick) uniquely identifies one (layer, microbatch) application
        and ``shard`` separates the dp ranks' slices, so dropout masks
        are independent across stages, microbatches AND data shards
        instead of one mask reused everywhere (ADVICE r5 medium).
        ``shard`` must stay 0 when data_axis is None — the batch is
        replicated there and per-device keys would desync the
        replicated computation
    stage_params: pytree, leaves stacked (L, ...) — layer l lives on
        device l % P (virtual pass l // P)
    x: (B, ...) global batch, split into ``num_microbatches`` chunks
        (default: P; interleaving needs m >= P)
    embed_fn(embed_params, micro) -> h   optional stage-0 prologue (e.g.
        token embedding); applied to each microbatch as it enters
    head_fn(head_params, outs) -> y      optional last-stage epilogue
        (e.g. vocab projection); applied batched to the collected
        pipeline outputs
    data_axis: name of a mesh axis to data-parallel over — each dp rank
        pipelines its own slice of every microbatch (independent pipe
        rings per dp shard); None replicates the batch across non-pipe
        axes (the pre-round-5 behavior)
    params_are_split: stage_params leaves already carry the (v, P, ...)
        leading dims (the layout a trainer keeps so optimizer state can
        shard over ``pipe``); False means flat (L, ...) stacks

    Returns the (B, ...) output of the final stage (after head_fn if
    given), replicated across the pipe axis (sharded over ``data_axis``
    when given).
    """
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if axis_name not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    p_size = int(mesh.shape[axis_name])
    v = int(num_virtual_stages)
    m = int(num_microbatches or p_size)
    b = x.shape[0]
    if b % m:
        raise MXNetError(f"batch {b} not divisible by {m} microbatches")
    if v > 1 and m < p_size:
        raise MXNetError(f"interleaved schedule needs microbatches >= "
                         f"pipeline depth ({m} < {p_size}): the wrapped "
                         f"activation of pass p must be back before its "
                         f"re-injection tick")
    leaves = jax.tree_util.tree_leaves(stage_params)
    if params_are_split:
        if leaves and leaves[0].shape[:2] != (v, p_size):
            raise MXNetError(f"params_are_split leaves must lead with "
                             f"(v, P) = ({v}, {p_size}); got "
                             f"{leaves[0].shape[:2]}")
    elif leaves and leaves[0].shape[0] != v * p_size:
        raise MXNetError(f"stage_params leading dim "
                         f"{leaves[0].shape[0]} != num_virtual_stages * "
                         f"pipe axis = {v * p_size}")
    if data_axis is not None:
        if data_axis not in mesh.axis_names:
            raise MXNetError(f"mesh has no axis {data_axis!r}")
        d_size = int(mesh.shape[data_axis])
        if (b // m) % d_size:
            raise MXNetError(
                f"per-microbatch size {b // m} (batch {b} / {m} "
                f"microbatches) not divisible by data axis "
                f"{data_axis}={d_size}")
    micro = x.reshape((m, b // m) + x.shape[1:])
    ticks = v * m + p_size - 1

    if not params_are_split:
        # (L, ...) -> (v, P, ...): pass-major split, P axis sharded
        stage_params = jax.tree_util.tree_map(
            lambda a: a.reshape((v, p_size) + a.shape[1:]), stage_params)
    param_spec = jax.tree_util.tree_map(
        lambda _: P(None, axis_name), stage_params)
    rep = jax.tree_util.tree_map(lambda _: P(), (embed_params,
                                                 head_params))
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    def body(params_local, e_params, h_params, micro_all):
        # params_local leaves: (v, 1, ...) — this device's layer stack
        d = lax.axis_index(axis_name)
        # dp shard identity for stage_ctx keys; MUST be 0 when the batch
        # is replicated (no data_axis) or per-device masks would desync
        # the replicated computation
        shard = lax.axis_index(data_axis) if data_axis is not None else 0
        is_first = d == 0
        is_last = d == p_size - 1
        micro_bs = micro_all.shape[1]

        # embed once, before the scan: inject() reads the pre-embedded
        # buffer so the (possibly expensive) lookup runs m times, not
        # P*(v*m+P-1) times
        embedded = micro_all if embed_fn is None else \
            jax.vmap(lambda mb: embed_fn(e_params, mb))(micro_all)

        def inject(t, wrap_buf):
            """Input for the unit device 0 starts at tick t: microbatch
            t%m, pass t//m — a fresh (embedded) microbatch on pass 0, a
            wrapped activation afterwards."""
            i0 = jnp.mod(t, m)
            fresh = embedded[i0]
            wrapped = jnp.take(wrap_buf, i0, axis=0)
            return jnp.where(t // m > 0, wrapped,
                             fresh.astype(wrapped.dtype))

        def tick(carry, t):
            wrap_buf, cur = carry
            inp = jnp.where(is_first, inject(t, wrap_buf), cur)
            # unit on this device: u = t - d; its virtual pass picks the
            # layer params (device d, pass p -> layer p*P + d)
            p_u = jnp.clip((t - d) // m, 0, v - 1)
            params_u = jax.tree_util.tree_map(
                lambda a: jnp.take(a, p_u, axis=0)[0], params_local)
            if stage_ctx:
                y = stage_fn(params_u, inp,
                             {"layer": p_u * p_size + d, "tick": t,
                              "shard": shard})
            else:
                y = stage_fn(params_u, inp)
            nxt = lax.ppermute(y, axis_name, perm)
            # what device 0 just received from device P-1 is unit
            # t-(P-1) finishing a pass: stash it for re-injection
            wrapped_i = jnp.mod(t - (p_size - 1), m)
            wrap_buf = lax.dynamic_update_index_in_dim(
                wrap_buf, nxt, wrapped_i, axis=0)
            return (wrap_buf, nxt), y

        probe_params = jax.tree_util.tree_map(lambda a: a[0, 0],
                                              params_local)
        probe = (stage_fn(probe_params, embedded[0],
                          {"layer": 0, "tick": 0, "shard": 0})
                 if stage_ctx else stage_fn(probe_params, embedded[0]))
        act0 = jnp.zeros_like(probe)
        # broadcast act0 in so the buffer carries the same varying-axis
        # type as the ppermute outputs that update it (shard_map vma)
        wrap0 = jnp.zeros((m,) + act0.shape, act0.dtype) + act0
        _, ys = lax.scan(tick, (wrap0, act0), jnp.arange(ticks))
        # microbatch i exits its LAST pass on device P-1 at tick
        # (v-1)*m + i + (P-1)
        outs = ys[(v - 1) * m + p_size - 1:]
        if head_fn is not None:
            outs = head_fn(h_params,
                           outs.reshape((m * micro_bs,) + outs.shape[2:]))
            outs = outs.reshape((m, micro_bs) + outs.shape[1:])
        outs = jnp.where(is_last, outs, jnp.zeros_like(outs))
        outs = lax.psum(outs, axis_name)       # broadcast from last stage
        return outs                            # (m, micro_bs_local, ...)

    batch_spec = P(None, data_axis) if data_axis is not None else P()
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(param_spec, rep[0], rep[1], batch_spec),
        out_specs=batch_spec)
    outs = fn(stage_params, embed_params, head_params, micro)
    return outs.reshape((b,) + outs.shape[2:])

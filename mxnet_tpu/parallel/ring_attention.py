"""Ring attention & blockwise (flash-style) attention — long-context
sequence/context parallelism, a net-new TPU capability (SURVEY §5.7: the
reference's longest-sequence story was BucketingModule padding; ring/Ulysses
postdate MXNet 1.x but are first-class here per the task spec).

Design:

- ``blockwise_attention``: single-device memory-efficient attention; online
  softmax over key/value blocks via ``lax.scan`` with rematerialized blocks
  (``jax.checkpoint``), so sequence length is bounded by HBM not VMEM.
- ``ring_attention``: the same online-softmax accumulation where key/value
  blocks live sharded over the ``seq`` mesh axis and rotate around the ICI
  ring via ``lax.ppermute`` (one neighbor hop per step — bandwidth-optimal,
  compute overlaps the permute under XLA's latency-hiding scheduler). Runs
  under ``shard_map``; differentiable end-to-end (ppermute transposes to the
  reverse permute).

Both support causal masking with *global* positions, so causal LM training
shards cleanly over the sequence axis.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

try:                                    # jax>=0.8 top-level; older versions
    from jax import shard_map           # under jax.experimental
except ImportError:                     # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["blockwise_attention", "ring_attention",
           "ulysses_attention", "attention_reference"]

_NEG = -1e30


def attention_reference(q, k, v, causal=False, scale=None):
    """Plain softmax(QK^T)V — the correctness oracle (the reference's
    full-attention BERT path, SURVEY §5.7) AND the production short-KV
    path of ops.contrib flash_attention (one definition, one mask
    convention). Causal masking is bottom-right aligned (query i attends
    keys j <= i + s_kv - s_q — the decode-cache convention); softmax row
    sums accumulate in fp32 via the shared shifted_expsum core, so bf16
    inputs never materialize an fp32 score tensor. Rows whose allowed-key
    set is empty (causal with s_q > s_kv) yield zeros."""
    from ..ops.tensor import shifted_expsum
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    mask = None
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    _, shifted, se32 = shifted_expsum(scores, axis=-1)
    w = (jnp.exp(shifted).astype(jnp.float32) / se32).astype(q.dtype)
    if mask is not None:
        w = w * mask.any(-1, keepdims=True).astype(w.dtype)
    return jnp.einsum("...qk,...kd->...qd", w, v)


def _online_block(carry, q, k_blk, v_blk, scale, mask=None):
    """One online-softmax accumulation step (the flash-attention update)."""
    o, l, m = carry
    scores = jnp.einsum("...qd,...kd->...qk", q, k_blk) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("...qk,...kd->...qd",
                                              p, v_blk.astype(p.dtype))
    return o_new, l_new, m_new


def blockwise_attention(q, k, v, block_size=512, causal=False, scale=None):
    """Memory-efficient attention over KV blocks (inputs [..., S, D]).

    Routed through the ``mxnet_tpu.pallas`` kernel registry: the online-
    softmax kernel is the custom tier (parity-gated against
    ``attention_reference`` by tests/test_pallas.py), so it shares the
    tier's kill-switch (``MXNET_TPU_PALLAS=off`` falls back to the dense
    reference), journaled-fallback, and provenance story with every other
    hand kernel."""
    from ..pallas import dispatch
    return dispatch("blockwise_attention", q, k, v, block_size=block_size,
                    causal=causal, scale=scale)


def _blockwise_impl(q, k, v, block_size=512, causal=False, scale=None):
    """The kernel body (dispatch target — call blockwise_attention)."""
    d = q.shape[-1]
    s_k = k.shape[-2]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    block_size = min(block_size, s_k)
    while s_k % block_size:        # shrink to the nearest divisor so any
        block_size -= 1            # sequence length works (block size is a
    n_blocks = s_k // block_size   # perf knob, not a correctness contract)
    kb = jnp.moveaxis(k.reshape(k.shape[:-2] + (n_blocks, block_size, d)),
                      -3, 0)
    vb = jnp.moveaxis(v.reshape(v.shape[:-2] + (n_blocks, block_size, d)),
                      -3, 0)
    s_q = q.shape[-2]
    # derive accumulators from q so their device-varying type matches under
    # shard_map (a plain zeros constant is 'unvarying' and scan rejects the
    # carry mismatch)
    zero_like_q = (q * 0).astype(jnp.float32)
    o0 = zero_like_q
    l0 = zero_like_q[..., 0]
    m0 = zero_like_q[..., 0] + _NEG
    q_pos = jnp.arange(s_q)

    @jax.checkpoint
    def step(carry, inputs):
        blk_idx, k_blk, v_blk = inputs
        mask = None
        if causal:
            # bottom-right aligned, matching attention_reference and the
            # short-KV path: query i attends keys j <= i + (s_k - s_q)
            k_pos = blk_idx * block_size + jnp.arange(block_size)
            mask = q_pos[:, None] + (s_k - s_q) >= k_pos[None, :]
            mask = jnp.broadcast_to(mask, carry[0].shape[:-1]
                                    + (block_size,))
        new = _online_block(carry, q.astype(jnp.float32),
                            k_blk.astype(jnp.float32), v_blk, scale, mask)
        return new, None

    (o, l, m), _ = lax.scan(step, (o0, l0, m0),
                            (jnp.arange(n_blocks), kb, vb))
    out = (o / l[..., None]).astype(q.dtype)
    if causal and s_q > s_k:
        # bottom-right alignment leaves queries i < s_q - s_k with an
        # empty allowed-key set; zero them like attention_reference does
        # (an all-masked row otherwise softmaxes uniformly over _NEG)
        valid = (jnp.arange(s_q) + (s_k - s_q) >= 0)
        out = out * valid[:, None].astype(out.dtype)
    return out


def _ring_body(q, k, v, axis_name, causal, scale, f32=jnp.float32):
    """Per-shard ring attention: local q stays, k/v rotate over the ring."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    s_local = q.shape[-2]
    d = q.shape[-1]
    o = jnp.zeros(q.shape[:-1] + (d,), f32)
    l = jnp.zeros(q.shape[:-1], f32)
    m = jnp.full(q.shape[:-1], _NEG, f32)
    qf = q.astype(f32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    q_pos = idx * s_local + jnp.arange(s_local)

    for step in range(n):
        src = (idx - step) % n           # which shard this k/v came from
        mask = None
        if causal:
            k_pos = src * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            mask = jnp.broadcast_to(mask, q.shape[:-1] + (s_local,))
        o, l, m = _online_block((o, l, m), qf, k.astype(f32), v, scale,
                                mask)
        if step != n - 1:
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
    return (o / l[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh = None, axis_name="seq",
                   causal=False, scale=None, batch_axis="data",
                   head_axis=None):
    """Sequence-parallel attention over the ``axis_name`` mesh ring.

    Inputs are GLOBAL arrays [B, H, S, D]; S is sharded over ``axis_name``,
    B over ``batch_axis`` (if present in the mesh), H over ``head_axis``
    (if given). Returns the global [B, H, S, D] output with the same
    sharding. Safe to call inside jit — shard_map composes.
    """
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if axis_name not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    d = q.shape[-1]
    scale = scale if scale is not None else float(1.0 / (d ** 0.5))
    b_ax = batch_axis if batch_axis in mesh.axis_names else None
    spec = P(b_ax, head_axis, axis_name, None)
    sh = NamedSharding(mesh, spec)
    # lay inputs out on the mesh: eager = real resharding onto the ring;
    # under jit = a sharding constraint GSPMD honors
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    body = functools.partial(_ring_body, axis_name=axis_name, causal=causal,
                             scale=scale)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh = None, axis_name="seq",
                      causal=False, scale=None, batch_axis="data"):
    """Ulysses/DeepSpeed-style sequence parallelism: instead of rotating
    K/V around the ring, one ``all_to_all`` re-shards [B,H,S,D] from
    S-sharded to H-sharded, each device runs FULL attention over its head
    slice, and a second all_to_all restores S-sharding. Preferable to ring
    attention when heads ≥ shards and the sequence fits per-device memory
    (2 collectives total vs P-1 permutes). SURVEY §5.7 names this as the
    alternative design; net-new vs the reference."""
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if axis_name not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    p = mesh.shape[axis_name]
    if q.shape[1] % p:
        raise MXNetError(f"num_heads {q.shape[1]} must be divisible by the "
                         f"{axis_name} axis size {p}")
    if q.shape[-2] % p:
        raise MXNetError(f"sequence length {q.shape[-2]} must be divisible "
                         f"by the {axis_name} axis size {p}")
    d = q.shape[-1]
    scale = scale if scale is not None else float(1.0 / (d ** 0.5))
    b_ax = batch_axis if batch_axis in mesh.axis_names else None
    spec = P(b_ax, None, axis_name, None)
    sh = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))

    def body(q_l, k_l, v_l):
        # local: [b, H, S/p, d] → all_to_all → [b, H/p, S, d]
        def scatter(x):
            return lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

        def gather(x):
            return lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)
        qh, kh, vh = scatter(q_l), scatter(k_l), scatter(v_l)
        # blockwise kernel keeps per-device memory O(block) not O(S^2) —
        # the long-context point of sequence parallelism
        out = blockwise_attention(qh, kh, vh, causal=causal, scale=scale)
        return gather(out)

    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return fn(q, k, v)

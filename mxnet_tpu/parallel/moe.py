"""Mixture-of-Experts with expert parallelism over an ``expert`` mesh axis
(net-new capability: MXNet 1.x has no MoE dispatch — SURVEY §2.4 #32).

Two formulations behind one axis convention:

- ``moe_apply`` — dense dispatch: every device computes its expert over
  the FULL token batch, masked by the gate, combined with one ``psum``.
  O(E·tokens) compute; robust at tiny expert counts and kept as the
  parity oracle.
- ``moe_apply_topk`` — the real path (GShard/Switch shape): tokens are
  sharded over the ``expert`` axis, routed top-k with a capacity factor,
  dispatched to their experts with ``lax.all_to_all`` over ICI, computed
  at O(k·tokens/E) per device, returned with a second all-to-all, and
  combined with normalized gate weights. Dispatch/combine are one-hot
  einsums — MXU work, not gathers — and overflow tokens beyond each
  expert's capacity are dropped (zero output), with the drop fraction
  and the Switch load-balancing auxiliary loss returned for training.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..base import MXNetError

try:
    from jax import shard_map
except ImportError:                      # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["moe_apply", "moe_apply_topk"]


def moe_apply(expert_fn, expert_params, gate_logits, x, mesh: Mesh = None,
              axis_name="expert"):
    """Top-1-routed mixture of experts.

    expert_fn(params_e, x) -> y       same signature for every expert
    expert_params: pytree with leaves stacked (E, ...), sharded over
        ``axis_name``
    gate_logits: (B, E) router scores (a Dense over x, computed outside)
    x: (B, D) tokens.

    Returns (B, D_out): each token processed by its argmax expert, scaled
    by the (differentiable) gate probability — Switch-transformer routing.
    """
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if axis_name not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    e_size = mesh.shape[axis_name]
    if gate_logits.shape[-1] != e_size:
        raise MXNetError(f"gate width {gate_logits.shape[-1]} != expert "
                         f"axis size {e_size}")
    param_spec = jax.tree_util.tree_map(lambda _: P(axis_name),
                                        expert_params)

    def body(params_local, gates, xs):
        e = lax.axis_index(axis_name)
        params_e = jax.tree_util.tree_map(lambda a: a[0], params_local)
        probs = jax.nn.softmax(gates, axis=-1)            # (B, E)
        top = jnp.argmax(probs, axis=-1)                  # (B,)
        weight = jnp.where(top == e, probs[:, e], 0.0)    # (B,)
        y = expert_fn(params_e, xs)                       # (B, D_out)
        y = y * weight[:, None].astype(y.dtype)
        return lax.psum(y, axis_name)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(param_spec, P(), P()),
                   out_specs=P())
    return fn(expert_params, gate_logits, x)


def moe_apply_topk(expert_fn, expert_params, gate_logits, x, k=2,
                   capacity_factor=1.25, mesh: Mesh = None,
                   axis_name="expert"):
    """Top-k routed MoE with all-to-all token dispatch (GShard/Switch).

    Tokens arrive sharded over ``axis_name``: ``x`` is the GLOBAL (B, D)
    batch, B divisible by the axis size E; device e owns rows
    [e*B/E, (e+1)*B/E). Each device routes its local tokens, exchanges
    them with two ``lax.all_to_all``s, and runs ONLY its own expert over
    at most k*B_local*capacity_factor tokens — per-device compute scales
    O(k·tokens/E), the property the dense formulation lacks.

    expert_fn(params_e, tokens) -> out      tokens (N, D) -> (N, D_out)
    expert_params: pytree, leaves stacked (E, ...), sharded over the axis
    gate_logits: (B, E) router scores
    k: experts per token (top-k gate probs, renormalized when k > 1)
    capacity_factor: each expert accepts ceil(k*B/E*cf) tokens; overflow
        tokens are dropped (zero contribution), first-choice slots fill
        before second-choice ones like GShard.

    Returns (y, aux_loss, stats):
      y        (B, D_out) — combined expert outputs (dropped tokens: 0)
      aux_loss scalar — E * Σ_e load_e · mean_prob_e (Switch §2.2);
               load counts all k choices, so perfect balance gives k
               (1.0 for top-1, 2.0 for the default top-2); add
               ~0.01·aux_loss to the loss
      stats    dict: 'dropped' — global fraction of (token, slot) pairs
               that overflowed capacity
    """
    from .mesh import current_mesh
    mesh = mesh or current_mesh()
    if axis_name not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    e_size = int(mesh.shape[axis_name])
    b_global, _ = x.shape
    if gate_logits.shape[-1] != e_size:
        raise MXNetError(f"gate width {gate_logits.shape[-1]} != expert "
                         f"axis size {e_size}")
    if b_global % e_size:
        raise MXNetError(f"batch {b_global} not divisible by expert axis "
                         f"{e_size}")
    b_local = b_global // e_size
    k = int(min(k, e_size))
    capacity = max(1, math.ceil(k * b_local * capacity_factor / e_size))
    param_spec = jax.tree_util.tree_map(lambda _: P(axis_name),
                                        expert_params)

    def body(params_local, gates, xs):
        # gates/xs are the LOCAL (B_l, ...) shards
        params_e = jax.tree_util.tree_map(lambda a: a[0], params_local)
        probs = jax.nn.softmax(gates.astype(jnp.float32), axis=-1)
        top_p, top_e = lax.top_k(probs, k)               # (B_l, k)
        if k > 1:
            top_p = top_p / jnp.maximum(
                top_p.sum(-1, keepdims=True), 1e-9)

        # capacity assignment, slot-major so every token's FIRST choice
        # claims buffer space before any second choice (GShard §3.2)
        flat_e = top_e.T.reshape(-1)                     # (k*B_l,)
        onehot = jax.nn.one_hot(flat_e, e_size,
                                dtype=jnp.float32)       # (kB, E)
        pos = jnp.cumsum(onehot, axis=0) * onehot        # 1-based slot
        pos = pos.sum(-1) - 1.0                          # (kB,)
        keep = (pos < capacity).astype(jnp.float32)
        pos_c = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)

        # dispatch mask (B_l, E, C) via one-hot products (MXU einsums)
        slot_oh = jax.nn.one_hot(pos_c, capacity,
                                 dtype=jnp.float32)      # (kB, C)
        mask = (onehot * keep[:, None])[:, :, None] * slot_oh[:, None, :]
        mask = mask.reshape(k, b_local, e_size, capacity)
        dispatch = mask.sum(0)                           # (B_l, E, C)
        gate_w = top_p.T.reshape(k, b_local, 1, 1)
        combine = (mask * gate_w).sum(0)                 # (B_l, E, C)

        # route tokens out: (E, C, D) then all-to-all over the axis so
        # device e ends up with every peer's C-token buffer for expert e
        x_disp = jnp.einsum("bec,bd->ecd", dispatch,
                            xs.astype(jnp.float32)).astype(xs.dtype)
        x_recv = lax.all_to_all(x_disp, axis_name, split_axis=0,
                                concat_axis=0, tiled=True)   # (E, C, D)
        y_loc = expert_fn(params_e,
                          x_recv.reshape(e_size * capacity, -1))
        y_loc = y_loc.reshape(e_size, capacity, -1)
        y_ret = lax.all_to_all(y_loc, axis_name, split_axis=0,
                               concat_axis=0, tiled=True)    # (E, C, Do)
        y = jnp.einsum("bec,ecd->bd", combine,
                       y_ret.astype(jnp.float32)).astype(x.dtype)

        # Switch load-balancing loss over the GLOBAL batch
        load = psum_mean(onehot.reshape(k, b_local, e_size).sum(0),
                         axis_name)                      # mean over B
        importance = psum_mean(probs, axis_name)
        aux = e_size * jnp.sum(load * importance)
        # keep already ranges over all k*B_local (token, slot) pairs, so
        # its global mean IS the kept fraction
        dropped = 1.0 - psum_mean(keep[:, None], axis_name).sum()
        return y, aux, dropped

    def psum_mean(v, ax):
        return lax.psum(v.mean(axis=0), ax) / e_size

    fn = shard_map(body, mesh=mesh,
                   in_specs=(param_spec, P(axis_name), P(axis_name)),
                   out_specs=(P(axis_name), P(), P()))
    y, aux, dropped = fn(expert_params, gate_logits, x)
    return y, aux, {"dropped": dropped}

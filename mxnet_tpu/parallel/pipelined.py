"""Gluon-level pipeline parallelism: train a real model (embedding → N
identical blocks → head) with pp × dp sharding WITHOUT hand-writing stage
closures — the trainer partitions the block list onto the ``pipe`` mesh
axis itself (VERDICT r4 Weak #4 / SURVEY §7 P7 "exposed as Gluon-level
options"; the reference's nearest tool is manual ``ctx_group`` placement,
example/model-parallel-lstm).

Design: the N body blocks must be structurally identical (a transformer
encoder stack) — their parameters stack into (v, P, ...) leaves, sharded
over ``pipe``, and ONE functional template block applies every layer
(pipeline.py's interleaved ppermute schedule). The embedding and head run
predicated on the edge devices with replicated parameters. Optimizer
state shards exactly like its weights, so per-device optimizer memory
scales 1/P for the body — the property Gluon-level pp exists for.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import _rng, autograd
from .. import ndarray as nd
from ..base import MXNetError
from ..guardrails import fused as _guard
from ..guardrails.trainer_mixin import GuardedTrainerMixin
from ..guardrails.monitor import AnomalyMonitor, GuardConfig
from ..observability import instrument as _obs
from .mesh import NamedSharding, PartitionSpec, use_mesh
from .pipeline import pipeline_apply
from .sharded import _opt_apply, _opt_init_state, functional_apply

__all__ = ["PipelinedTrainer"]


def _trainable_of(block):
    trainable, aux = block._param_split()
    if aux:
        raise MXNetError(
            f"PipelinedTrainer: block {type(block).__name__} has auxiliary "
            "state (BatchNorm running stats); pipeline stages must be "
            "aux-free (use LayerNorm — the transformer norm — or train "
            "with ShardedTrainer)")
    # MoE layers stash an aux loss for ShardedTrainer's collector; the
    # pipelined step doesn't collect it (a per-tick tracer inside the
    # shard_map can't be summed after the fact), so train MoE models with
    # ShardedTrainer on an expert mesh instead of silently dropping the
    # load-balancing term here
    stack, seen = [block], set()
    while stack:
        b = stack.pop()
        if id(b) in seen:
            continue
        seen.add(id(b))
        if getattr(b, "aux_loss_weight", None) is not None:
            raise MXNetError(
                f"PipelinedTrainer: {type(b).__name__} carries an "
                "auxiliary loss (MoE load balancing) that the pipelined "
                "step would silently drop; use ShardedTrainer with a "
                "data x expert mesh for MoE models")
        stack.extend(getattr(b, "_children", {}).values())
    return trainable


class PipelinedTrainer(GuardedTrainerMixin):
    """Pipeline + data parallel Gluon training driver::

        emb  = gluon.nn.Embedding(vocab, d)
        body = [TransformerLayer(d, heads) for _ in range(8)]
        head = gluon.nn.Dense(vocab)
        mesh = parallel.make_mesh({"pipe": 2, "data": 4})
        tr = parallel.PipelinedTrainer(emb, body, head,
            gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 1e-3}, mesh=mesh, num_microbatches=4)
        loss = tr.step(tokens, labels)     # ONE fused XLA program

    The 8 body layers live 4-per-device on the 2-way ``pipe`` axis
    (interleaved schedule when ``num_virtual_stages > 1``); every dp rank
    runs its own pipeline ring over its slice of the batch, and gradient
    all-reduce over ``data`` is derived by GSPMD from the mean loss.

    Restrictions (v1, raised eagerly): body blocks must be structurally
    identical and aux-free, with matching input/output activation shapes;
    per-parameter lr/wd multipliers are not applied (the stacked layout
    has no per-parameter identity). Dropout masks are independent per
    (layer, microbatch, dp shard) — the scan body folds layer identity,
    the schedule tick and the data-axis index into the key — but the
    draw ORDER differs from the
    sequential dp-only model, so bit-parity tests against ShardedTrainer
    should use dropout=0 (mode-off parity via ``evaluate`` holds at any
    dropout rate).
    """

    _guard_consumer = "pipelined_trainer"

    def __init__(self, embed, body_blocks, head, loss_fn, optimizer,
                 optimizer_params=None, mesh=None, num_microbatches=None,
                 num_virtual_stages=1, pipe_axis="pipe", data_axis="data",
                 donate=True, guard=None):
        from .. import optimizer as opt_mod
        from .mesh import current_mesh
        self._embed, self._body, self._head = embed, list(body_blocks), head
        self._loss = loss_fn
        optimizer_params = optimizer_params or {}
        self._optimizer = (optimizer
                           if isinstance(optimizer, opt_mod.Optimizer)
                           else opt_mod.create(optimizer, **optimizer_params))
        self._mesh = mesh or current_mesh()
        if pipe_axis not in self._mesh.axis_names:
            raise MXNetError(f"mesh has no axis {pipe_axis!r}")
        if data_axis is not None and \
                data_axis not in self._mesh.axis_names and \
                data_axis != "data":
            # an explicitly-requested dp axis that doesn't exist must fail
            # loudly — silently replicating would waste every dp rank; the
            # DEFAULT "data" merely degrades to pipe-only (a pure-pp mesh
            # is legitimate)
            raise MXNetError(f"mesh has no axis {data_axis!r}")
        self._pipe_axis, self._data_axis = pipe_axis, data_axis
        self._p = int(self._mesh.shape[pipe_axis])
        self._v = int(num_virtual_stages)
        if len(self._body) != self._v * self._p:
            raise MXNetError(
                f"{len(self._body)} body blocks don't tile onto "
                f"num_virtual_stages * pipe = {self._v} * {self._p}; add "
                f"blocks or change num_virtual_stages")
        self._m = num_microbatches
        self._donate = donate
        self._prepared = False
        self._num_update = self._optimizer.begin_num_update
        self._step_fn = None
        # anomaly guardrails — same contract as ShardedTrainer (the flag
        # and norm are in-program outputs of every step); fp16 via
        # amp.init("float16") rides a DynamicLossScaler on the same flag
        self._guard_cfg = GuardConfig.coerce(guard)
        self._monitor = (AnomalyMonitor(self._guard_cfg,
                                        consumer=self._guard_consumer)
                         if self._guard_cfg is not None else None)
        self._scaler = None
        self._resolve_scaler()
        self._guard_state = None
        self._skipped_offset = 0

    def _resolve_scaler(self):
        """(Re)resolve the fp16 loss scaler from the LIVE amp state —
        at construction and again at first trace (_prepare). The
        forward's amp casts resolve at trace time, so a scaler frozen
        from stale __init__ state would desynchronize from the
        program's actual dtype: amp.init("float16") between
        construction and the first step must still get loss scaling."""
        from ..contrib.amp import amp_dtype
        if amp_dtype() == "float16":
            if self._scaler is None:
                from ..contrib.amp import DynamicLossScaler
                self._scaler = DynamicLossScaler()
        else:
            self._scaler = None
        self._validate_guard_mode()

    # -- setup ---------------------------------------------------------------
    def _prepare(self, x_example):
        if self._prepared:
            return
        self._resolve_scaler()
        with use_mesh(self._mesh):
            h = self._embed(x_example if isinstance(x_example, nd.NDArray)
                            else nd.array(x_example))
            body_out = self._body[0](h)
            if tuple(body_out.shape) != tuple(h.shape):
                raise MXNetError(
                    f"body blocks must preserve the activation shape (they "
                    f"ride one ppermute ring): {tuple(h.shape)} -> "
                    f"{tuple(body_out.shape)}")
            for blk in self._body[1:]:
                blk(h)            # materialize deferred shapes identically
            self._head(body_out)
        self._e_params = _trainable_of(self._embed)
        self._h_params = _trainable_of(self._head)
        body_params = [_trainable_of(b) for b in self._body]
        shapes0 = [tuple(p._data[0].shape) for p in body_params[0]]
        for i, plist in enumerate(body_params):
            if [tuple(p._data[0].shape) for p in plist] != shapes0:
                raise MXNetError(
                    f"body block {i} has a different parameter signature "
                    "than block 0 — pipeline stages must be structurally "
                    "identical")
        rep = NamedSharding(self._mesh, PartitionSpec())

        # stacked body leaves: (v, P, ...), layer l = pass l//P on device l%P
        # (pipeline.py's pass-major layout), sharded over pipe so weights
        # AND optimizer state scale 1/P per device
        def split_spec(_):
            return PartitionSpec(None, self._pipe_axis)
        self._b_spec = NamedSharding(self._mesh, split_spec(None))
        self._b_datas = []
        for j in range(len(shapes0)):
            stack = jnp.stack([body_params[i][j]._data[0]._data
                               for i in range(len(body_params))])
            stack = stack.reshape((self._v, self._p) + stack.shape[1:])
            self._b_datas.append(jax.device_put(stack, self._b_spec))
        for p in self._e_params + self._h_params:
            p._data[0]._rebind(jax.device_put(p._data[0]._data, rep))

        opt = self._optimizer
        self._e_states = [tuple(jax.device_put(s, rep)
                                for s in _opt_init_state(opt, p._data[0]._data))
                          for p in self._e_params]
        self._h_states = [tuple(jax.device_put(s, rep)
                                for s in _opt_init_state(opt, p._data[0]._data))
                          for p in self._h_params]
        self._b_states = [tuple(jax.device_put(s, self._b_spec
                                               if getattr(s, "ndim", 0)
                                               else rep)
                                for s in _opt_init_state(opt, w))
                          for w in self._b_datas]
        self._guard_state = self._reinit_guard_state()
        self._prepared = True

    # -- the compiled pp × dp step -------------------------------------------
    def _make_forward(self, training):
        """ONE pipeline-forward closure shared by step() and evaluate() —
        the schedule, key folding and sharding must never drift between
        the trained model and the evaluated one."""
        embed_blk, body_blk, head_blk = self._embed, self._body[0], self._head
        mesh, pipe, data = self._mesh, self._pipe_axis, self._data_axis
        m, v = self._m, self._v

        def forward(e_tr, b_tr, h_tr, key, xb):
            def embed_fn(ep, mb):
                outs, _, _ = functional_apply(
                    embed_blk, jax.random.fold_in(key, 1), ep, [], [mb],
                    training=training)
                return outs[0]

            def stage_fn(pl, hact, ctx):
                # fold layer identity, schedule tick AND dp shard into
                # the key: (layer, tick) names one (layer, microbatch)
                # application and shard separates the dp ranks' slices,
                # so every stage/microbatch/shard draws an independent
                # dropout mask — one shared mask silently correlates
                # regularization (ADVICE r5 medium)
                k = jax.random.fold_in(jax.random.fold_in(
                    jax.random.fold_in(jax.random.fold_in(key, 2),
                                       ctx["layer"]), ctx["tick"]),
                    ctx["shard"])
                outs, _, _ = functional_apply(
                    body_blk, k, pl, [], [hact], training=training)
                return outs[0]

            def head_fn(hp, hs):
                outs, _, _ = functional_apply(
                    head_blk, jax.random.fold_in(key, 3), hp, [], [hs],
                    training=training)
                return outs[0]

            return pipeline_apply(
                stage_fn, list(b_tr), xb, mesh=mesh, axis_name=pipe,
                num_microbatches=m, num_virtual_stages=v,
                embed_fn=embed_fn, embed_params=list(e_tr),
                head_fn=head_fn, head_params=list(h_tr),
                data_axis=(data if data in mesh.axis_names else None),
                params_are_split=True, stage_ctx=True)
        return forward

    def _build_step(self):
        loss_block, opt = self._loss, self._optimizer
        clip = opt.clip_gradient if opt.clip_gradient is not None else -1.0
        wd = opt.wd
        fwd = self._make_forward(training=True)

        guard_clip = (self._guard_cfg.clip_norm
                      if self._guard_cfg is not None else None)
        # static at trace time: no guard + no fp16 scaler -> apply the
        # update unconditionally (a silent unjournaled skip would freeze
        # training invisibly; sharded.py has the same contract)
        guarded = self._scaler is not None or self._guard_cfg is not None

        def step(e_tr, b_tr, h_tr, e_st, b_st, h_st, gstate, key, lr, t,
                 rescale, lscale, x, y):
            def loss_of(groups):
                e_tr_, b_tr_, h_tr_ = groups
                out = fwd(e_tr_, b_tr_, h_tr_, key, x)
                out_nd = nd.NDArray(out.astype(jnp.float32),
                                    _skip_device_put=True)
                y_nd = nd.NDArray(y, _skip_device_put=True)
                with autograd.pause(train_mode=True):
                    loss_nd = loss_block(out_nd, y_nd)
                loss_val = jnp.mean(loss_nd._data.astype(jnp.float32))
                # fp16: grads see the scaled loss; the report stays
                # unscaled (same contract as ShardedTrainer)
                return loss_val * lscale, loss_val

            (_, loss_val), grads = jax.value_and_grad(
                loss_of, has_aux=True)((list(e_tr), list(b_tr),
                                        list(h_tr)))
            # fused guard over every stage's grads: the flag is agreed
            # across the whole pipe x data mesh (grads are the derived
            # psum results), so every rank skips or none does
            inv = jnp.float32(1.0) / lscale
            finite, gnorm_scaled = _guard.guard_stats(grads, loss_val)
            gnorm = gnorm_scaled * inv
            rescale_all = rescale * inv
            if guard_clip is not None:
                rescale_all = rescale_all * _guard.clip_scale(
                    gnorm * rescale, jnp.float32(guard_clip))

            def upd(ws, gs, sts):
                new_w, new_s = [], []
                for w, g, s in zip(ws, gs, sts):
                    w2, s2 = _opt_apply(opt, w, g, s, lr, t, wd,
                                        rescale_all, clip)
                    new_w.append(w2)
                    new_s.append(s2)
                return new_w, new_s

            e2, es2 = upd(e_tr, grads[0], e_st)
            b2, bs2 = upd(b_tr, grads[1], b_st)
            h2, hs2 = upd(h_tr, grads[2], h_st)
            # skip-step: non-finite -> bitwise no-op for every group
            if guarded:
                e2 = _guard.select(finite, e2, list(e_tr))
                b2 = _guard.select(finite, b2, list(b_tr))
                h2 = _guard.select(finite, h2, list(h_tr))
                es2 = _guard.select(finite, es2, list(e_st))
                bs2 = _guard.select(finite, bs2, list(b_st))
                hs2 = _guard.select(finite, hs2, list(h_st))
                gstate2 = _guard.update_guard_state(gstate, finite)
            else:
                gstate2 = gstate
            return (e2, b2, h2, es2, bs2, hs2, gstate2, loss_val,
                    (finite, gnorm))

        ns = lambda spec: NamedSharding(self._mesh, spec)
        rep = ns(PartitionSpec())
        bsp = self._b_spec
        st_sh = lambda sts, sh: [tuple(sh if getattr(e, "ndim", 0) else rep
                                       for e in st) for st in sts]
        in_sh = ([rep] * len(self._e_params), [bsp] * len(self._b_datas),
                 [rep] * len(self._h_params),
                 st_sh(self._e_states, rep), st_sh(self._b_states, bsp),
                 st_sh(self._h_states, rep),
                 (rep, rep), rep, rep, rep, rep, rep, None, None)
        out_sh = in_sh[:6] + ((rep, rep), rep, (rep, rep))
        donate = (0, 1, 2, 3, 4, 5) if self._donate else ()
        self._raw_step = step
        self._sharding_cfg = (in_sh, out_sh, donate)
        return jax.jit(step, in_shardings=in_sh, out_shardings=out_sh,
                       donate_argnums=donate)

    def _lr_at(self, t):
        from .sharded import _lr_at
        return _lr_at(self._optimizer, t)

    def _apply_results(self, results):
        """Shared dispatch tail for step/run_steps: rebind updated
        params + state + guard counters, return the guard outputs."""
        e2, b2, h2, es2, bs2, hs2, gstate, loss, flag = results
        for p, w in zip(self._e_params, e2):
            p._data[0]._rebind(w)
        for p, w in zip(self._h_params, h2):
            p._data[0]._rebind(w)
        self._b_datas = list(b2)
        self._e_states, self._b_states, self._h_states = \
            list(es2), list(bs2), list(hs2)
        self._guard_state = gstate
        return loss, flag

    # guard bookkeeping (_after_step/_after_run_steps/_handle_divergence/
    # skipped_steps/guard_poll) comes from GuardedTrainerMixin
    def _reinit_guard_state(self):
        rep = NamedSharding(self._mesh, PartitionSpec())
        return tuple(jax.device_put(s, rep)
                     for s in _guard.init_guard_state())

    def step(self, x, y):
        """One fused pp × dp train step; returns the scalar loss."""
        self._prepare(x)
        if self._m is None:
            self._m = self._p
        compiling = self._step_fn is None
        if compiling:
            self._step_fn = self._build_step()
        self._num_update += 1
        t = self._num_update
        # telemetry (docs/observability.md): always-on phase summaries
        # (host clock only), spans under MXNET_TPU_TRACE
        with _obs.trace.span("pipelined_trainer.step", step=t):
            with _obs.step_phase("pipelined_trainer", "data_wait"):
                xd = x._data if isinstance(x, nd.NDArray) \
                    else jnp.asarray(x)
                yd = y._data if isinstance(y, nd.NDArray) \
                    else jnp.asarray(y)
            self._optimizer.num_update = t
            lscale = (self._scaler.loss_scale
                      if self._scaler is not None else 1.0)
            e_tr = [p._data[0]._data for p in self._e_params]
            h_tr = [p._data[0]._data for p in self._h_params]
            cshapes = ([list(map(int, np.shape(v))) for v in (xd, yd)]
                       if compiling else None)
            with _obs.step_phase("pipelined_trainer", "compiled_step"), \
                    _obs.maybe_compile_span(compiling,
                                            "pipelined_trainer.step",
                                            shapes=cshapes), \
                    use_mesh(self._mesh):
                results = self._step_fn(
                    e_tr, self._b_datas, h_tr, self._e_states,
                    self._b_states, self._h_states, self._guard_state,
                    _rng.next_key(), jnp.float32(self._lr_at(t)),
                    jnp.float32(t),
                    jnp.float32(self._optimizer.rescale_grad),
                    jnp.float32(lscale), xd, yd)
            loss, (finite, gnorm) = self._apply_results(results)
            with _obs.step_phase("pipelined_trainer", "guard_fetch"):
                self._after_step(t, loss, finite, gnorm)
        return nd.NDArray(loss, _skip_device_put=True)

    def run_steps(self, x, y, num_steps=8):
        """Run ``num_steps`` train steps as ONE compiled program
        (``lax.scan`` over the step body, batch reused each inner step) —
        ShardedTrainer.run_steps parity: host/tunnel dispatch latency is
        amortized across the scan instead of paid per step. Returns the
        last step's loss."""
        self._prepare(x)
        if self._m is None:
            self._m = self._p
        if self._step_fn is None:
            self._step_fn = self._build_step()
        key = f"multi{num_steps}"
        if not hasattr(self, "_multi_fns"):
            self._multi_fns = {}
        compiling = key not in self._multi_fns
        if compiling:
            raw = self._raw_step
            in_sh, out_sh, donate = self._sharding_cfg
            rep = NamedSharding(self._mesh, PartitionSpec())

            def multi(e_tr, b_tr, h_tr, e_st, b_st, h_st, gstate, rng,
                      lrs, t, rescale, lscale, x, y):
                # lrs: (num_steps,) — the scheduler is evaluated on the
                # host for EVERY inner step, so a warmup/cosine schedule
                # sees the same lr sequence as num_steps step() calls
                def body(carry, i):
                    e, b, h, es, bs, hs, gs, t_ = carry
                    k = jax.random.fold_in(rng, i)
                    e2, b2, h2, es2, bs2, hs2, gs2, loss, (fin, gn) = raw(
                        e, b, h, es, bs, hs, gs, k, lrs[i], t_, rescale,
                        lscale, x, y)
                    return (e2, b2, h2, es2, bs2, hs2, gs2, t_ + 1.0), \
                        (loss, fin, gn)

                carry, (losses, fins, gns) = jax.lax.scan(
                    body, (e_tr, b_tr, h_tr, e_st, b_st, h_st, gstate, t),
                    jnp.arange(num_steps))
                return carry[:7] + (losses, fins, gns)

            self._multi_fns[key] = jax.jit(
                multi, in_shardings=in_sh,
                out_shardings=out_sh[:7] + (rep, rep, rep),
                donate_argnums=donate)
        t = self._num_update + 1
        self._num_update += num_steps
        with _obs.trace.span("pipelined_trainer.run_steps", start_step=t,
                             num_steps=num_steps):
            with _obs.step_phase("pipelined_trainer", "data_wait"):
                xd = x._data if isinstance(x, nd.NDArray) \
                    else jnp.asarray(x)
                yd = y._data if isinstance(y, nd.NDArray) \
                    else jnp.asarray(y)
            self._optimizer.num_update = self._num_update
            from .sharded import _lr_sequence
            lrs = _lr_sequence(self._optimizer, t, num_steps)
            lscale = (self._scaler.loss_scale
                      if self._scaler is not None else 1.0)
            e_tr = [p._data[0]._data for p in self._e_params]
            h_tr = [p._data[0]._data for p in self._h_params]
            cshapes = ([list(map(int, np.shape(v))) for v in (xd, yd)]
                       if compiling else None)
            with _obs.step_phase("pipelined_trainer", "compiled_step"), \
                    _obs.maybe_compile_span(
                        compiling, "pipelined_trainer.run_steps",
                        num_steps=num_steps, shapes=cshapes), \
                    use_mesh(self._mesh):
                results = self._multi_fns[key](
                    e_tr, self._b_datas, h_tr, self._e_states,
                    self._b_states, self._h_states, self._guard_state,
                    _rng.next_key(), lrs, jnp.float32(t),
                    jnp.float32(self._optimizer.rescale_grad),
                    jnp.float32(lscale), xd, yd)
            losses, fins, gns = results[7], results[8], results[9]
            self._apply_results(results[:7] + (losses[-1], (fins[-1],
                                                            gns[-1])))
            with _obs.step_phase("pipelined_trainer", "guard_fetch"):
                self._after_run_steps(t, losses, fins, gns)
        return nd.NDArray(losses[-1], _skip_device_put=True)

    def evaluate(self, x, y):
        """Forward + loss through the pipeline, no update (ShardedTrainer
        .evaluate parity). Runs the SAME schedule as step() in inference
        mode (dropout off) under a FIXED key — evaluation is RNG-neutral:
        it never advances the global stream, so interleaving eval with
        training cannot change the training trajectory."""
        self._prepare(x)
        if self._m is None:
            self._m = self._p
        if getattr(self, "_eval_fn", None) is None:
            loss_block = self._loss
            fwd = self._make_forward(training=False)

            def eval_step(e_tr, b_tr, h_tr, key, xb, yb):
                out = fwd(e_tr, b_tr, h_tr, key, xb)
                out_nd = nd.NDArray(out.astype(jnp.float32),
                                    _skip_device_put=True)
                y_nd = nd.NDArray(yb, _skip_device_put=True)
                with autograd.pause(train_mode=False):
                    loss_nd = loss_block(out_nd, y_nd)
                return jnp.mean(loss_nd._data.astype(jnp.float32))

            self._eval_fn = jax.jit(eval_step)
        xd = x._data if isinstance(x, nd.NDArray) else jnp.asarray(x)
        yd = y._data if isinstance(y, nd.NDArray) else jnp.asarray(y)
        # params are mesh-committed; the batch must live on the same
        # device set or the unsharded jit refuses the mix
        rep = NamedSharding(self._mesh, PartitionSpec())
        xd, yd = jax.device_put(xd, rep), jax.device_put(yd, rep)
        e_tr = [p._data[0]._data for p in self._e_params]
        h_tr = [p._data[0]._data for p in self._h_params]
        with use_mesh(self._mesh):
            # eval runs dropout-off under a FIXED key by design (see the
            # docstring above): RNG-neutral, never advances any stream
            loss = self._eval_fn(
                e_tr, self._b_datas, h_tr,
                jax.random.PRNGKey(0),  # graftlint: disable=G2 RNG-neutral eval
                xd, yd)
        return nd.NDArray(loss, _skip_device_put=True)

    # -- checkpoint / resume (same file machinery + guarantees as
    # ShardedTrainer: bit-exact, per-shard-capable; parallel/_ckpt.py) ------
    def _ckpt_entries(self):
        ent = {}
        for i, p in enumerate(self._e_params):
            ent[f"arg:embed:{i}"] = p._data[0]._data
        for j, w in enumerate(self._b_datas):
            ent[f"arg:body:{j}"] = w
        for i, p in enumerate(self._h_params):
            ent[f"arg:head:{i}"] = p._data[0]._data
        for grp, states in (("embed", self._e_states),
                            ("body", self._b_states),
                            ("head", self._h_states)):
            for i, st in enumerate(states):
                for k, s in enumerate(st):
                    ent[f"state:{grp}:{i}:{k}"] = s
        return ent

    def save_checkpoint(self, prefix, per_shard=None):
        """Snapshot pipe-sharded body stacks + replicated edge params +
        optimizer state + step + RNG into ``<prefix>.pstate``."""
        self._require_prepared()
        from . import _ckpt
        if per_shard is None:
            per_shard = _ckpt.group().count() > 1
        meta = {
            "format": _ckpt.CKPT_FORMAT,
            "kind": "pipelined",
            "optimizer": type(self._optimizer).__name__,
            "num_update": int(self._num_update),
            "pipe": self._p, "virtual": self._v,
            "per_shard": bool(per_shard),
            "shard_files": _ckpt.group().count(),
        }
        meta.update(_ckpt.rng_meta())
        _ckpt.write_entries(f"{prefix}.pstate", self._ckpt_entries(), meta)

    def load_checkpoint(self, prefix):
        """Bit-exact resume onto a prepared trainer with the same blocks,
        optimizer class and pipe/virtual layout."""
        self._require_prepared()
        from . import _ckpt
        meta, loaded = _ckpt.read_meta(f"{prefix}.pstate")
        if meta.get("kind") != "pipelined":
            raise MXNetError(f"{prefix}.pstate is not a PipelinedTrainer "
                             "checkpoint")
        if meta["optimizer"] != type(self._optimizer).__name__:
            raise MXNetError(
                f"checkpoint optimizer {meta['optimizer']!r} != "
                f"{type(self._optimizer).__name__!r}")
        if (meta["pipe"], meta["virtual"]) != (self._p, self._v):
            raise MXNetError(
                f"checkpoint pipeline layout pipe={meta['pipe']} "
                f"v={meta['virtual']} != trainer pipe={self._p} "
                f"v={self._v}")
        ents = self._ckpt_entries()
        pieces = (_ckpt.read_pieces(f"{prefix}.pstate",
                                    int(meta.get("shard_files", 1)),
                                    _ckpt.needed_piece_keys(ents))
                  if meta["per_shard"] else None)
        self._place_all(lambda name: _ckpt.place_like(
            name, ents[name], loaded, pieces))
        self._num_update = int(meta["num_update"])
        self._optimizer.num_update = self._num_update
        _ckpt.restore_rng(meta)

    def checkpoint(self, ckpt_dir, step=None, keep_last=None,
                   per_shard=None):
        """Crash-consistent directory checkpoint — same commit protocol
        as ``ShardedTrainer.checkpoint`` (stage → rank-0 CRC manifest →
        rename publish → latest pointer → keep-last-k GC). Returns the
        committed step."""
        self._require_prepared()
        from . import _ckpt
        step = int(self._num_update if step is None else step)
        return _ckpt.commit_checkpoint(
            ckpt_dir, step,
            lambda prefix: self.save_checkpoint(prefix,
                                                per_shard=per_shard),
            keep_last=keep_last)

    def restore(self, ckpt_dir, step=None, latest=True):
        """Resume from the newest valid committed step under
        ``ckpt_dir`` (corrupt candidates skipped with a journaled
        ``ckpt_fallback``). Returns the restored step."""
        self._require_prepared()
        from . import _ckpt
        if step is None and not latest:
            raise MXNetError("restore needs step=N or latest=True")
        return _ckpt.restore_checkpoint(ckpt_dir, self.load_checkpoint,
                                        step=step)

    def load_checkpoint_resharded(self, prefix):
        """Topology-aware twin of :meth:`load_checkpoint`
        (docs/elastic.md): assemble the global stacks from however many
        shard files the saving cohort wrote and re-place them onto THIS
        trainer's mesh. The pipe/virtual layout must still match — the
        stacked body weights embed it structurally; changing it means
        building a fresh trainer, which this method then restores."""
        self._require_prepared()
        from . import _ckpt
        from ..elastic import reshard as _reshard
        meta, entries = _reshard.read_global_entries(f"{prefix}.pstate")
        if meta.get("kind") != "pipelined":
            raise MXNetError(f"{prefix}.pstate is not a PipelinedTrainer "
                             "checkpoint")
        if meta["optimizer"] != type(self._optimizer).__name__:
            raise MXNetError(
                f"checkpoint optimizer {meta['optimizer']!r} != "
                f"{type(self._optimizer).__name__!r}")
        if (meta["pipe"], meta["virtual"]) != (self._p, self._v):
            raise MXNetError(
                f"checkpoint pipeline layout pipe={meta['pipe']} "
                f"v={meta['virtual']} != trainer pipe={self._p} "
                f"v={self._v}")
        ents = self._ckpt_entries()

        def place(name):
            if name not in entries:
                raise MXNetError(f"checkpoint is missing entry {name!r}")
            return _reshard.place_global(name, ents[name], entries[name])

        self._place_all(place)
        self._num_update = int(meta["num_update"])
        self._optimizer.num_update = self._num_update
        _ckpt.restore_rng(meta)
        _reshard.journal_reshard(prefix, self._num_update, meta,
                                 _ckpt.group().count(), entries,
                                 self._guard_consumer)

    def restore_resharded(self, ckpt_dir, step=None):
        """Newest valid committed step under ``ckpt_dir`` restored onto
        the current topology, whatever world size wrote it."""
        self._require_prepared()
        from . import _ckpt
        return _ckpt.restore_checkpoint(
            ckpt_dir, self.load_checkpoint_resharded, step=step)

    def _place_all(self, get):
        """Rebind every stack leaf through ``get(name)`` — the ONE
        traversal (``_ckpt_entries`` names) the resharded load and the
        cohort sync share."""
        for i, p in enumerate(self._e_params):
            p._data[0]._rebind(get(f"arg:embed:{i}"))
        for i, p in enumerate(self._h_params):
            p._data[0]._rebind(get(f"arg:head:{i}"))
        self._b_datas = [get(f"arg:body:{j}")
                         for j in range(len(self._b_datas))]
        self._e_states = [tuple(get(f"state:embed:{i}:{k}")
                                for k in range(len(st)))
                          for i, st in enumerate(self._e_states)]
        self._b_states = [tuple(get(f"state:body:{i}:{k}")
                                for k in range(len(st)))
                          for i, st in enumerate(self._b_states)]
        self._h_states = [tuple(get(f"state:head:{i}:{k}")
                                for k in range(len(st)))
                          for i, st in enumerate(self._h_states)]

    def _adopt_host_entries(self, entries):
        """Re-place host arrays over the live stacks keeping current
        shardings — the elastic driver's cohort sync point. Names
        absent from ``entries`` keep their current value."""
        from ..elastic import reshard as _reshard
        ents = self._ckpt_entries()
        self._place_all(
            lambda name: (_reshard.place_global(name, ents[name],
                                                entries[name])
                          if name in entries else ents[name]))

    def prepare(self, x_example):
        """Materialize stacked/sharded state without stepping (the resume
        entry point: prepare, then ``load_checkpoint``)."""
        self._prepare(x_example)

    def unstack_to_blocks(self):
        """Write the stacked body weights back into the individual Gluon
        blocks (after training, e.g. for save_parameters/export)."""
        self._require_prepared()
        for j, stack in enumerate(self._b_datas):
            flat = np.asarray(stack).reshape(
                (self._v * self._p,) + stack.shape[2:])
            for i, blk in enumerate(self._body):
                plist = _trainable_of(blk)
                plist[j]._data[0]._rebind(jnp.asarray(flat[i]))

    def _require_prepared(self):
        if not self._prepared:
            raise MXNetError("PipelinedTrainer: run a step first")

    @property
    def num_update(self):
        """Completed optimizer updates (restored by load_checkpoint)."""
        return self._num_update

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

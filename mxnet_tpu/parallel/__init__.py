"""mxnet_tpu.parallel — meshes, shardings, and the single-program SPMD
training path (net-new TPU capability; see SURVEY §2.4 #32 and §5.8: the
reference's KVStore/executor-group data parallelism plus the parallelisms
MXNet 1.x never had, expressed as GSPMD shardings on one device mesh)."""
from .mesh import (Mesh, NamedSharding, PartitionSpec, current_mesh,
                   data_parallel_spec, default_mesh, make_mesh,
                   mesh_signature, replicated, use_mesh)
from .moe import moe_apply, moe_apply_topk
from .pipeline import pipeline_apply, pipeline_schedule_info
from .pipelined import PipelinedTrainer
from .ring_attention import (attention_reference, blockwise_attention,
                             ring_attention, ulysses_attention)
from .sharded import (ShardedTrainer, allreduce_across_processes,
                      functional_apply)

__all__ = ["Mesh", "NamedSharding", "PartitionSpec", "current_mesh",
           "data_parallel_spec", "default_mesh", "make_mesh",
           "mesh_signature", "replicated",
           "use_mesh", "ShardedTrainer", "allreduce_across_processes",
           "functional_apply", "ring_attention", "blockwise_attention",
           "ulysses_attention", "attention_reference", "pipeline_apply", "pipeline_schedule_info",
           "moe_apply", "moe_apply_topk", "PipelinedTrainer"]

"""Device-mesh construction — the TPU-native substrate for every parallelism.

The reference discovers topology per-backend: CUDA P2P probing for
``CommDevice`` (ref: src/kvstore/comm.h EnableP2P), NCCL ring setup for
``KVStoreNCCL`` (ref: src/kvstore/kvstore_nccl.h), DMLC env wiring for
ps-lite clusters (ref: 3rdparty/ps-lite/src/postoffice.cc). On TPU all of
that collapses to ONE object: a ``jax.sharding.Mesh`` over the pod slice.
Collectives ride ICI within a slice and DCN across slices; XLA picks the
ring/tree schedule (the reference's ``CommDeviceTree`` heuristics are the
compiler's job here).

Axis-name conventions used throughout the framework:
  ``data``   — data parallel (batch dim)
  ``model``  — tensor/model parallel (hidden dims)
  ``seq``    — sequence/context parallel (ring attention)
  ``pipe``   — pipeline stages
"""
from __future__ import annotations

import math
from contextlib import contextmanager

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError

__all__ = ["make_mesh", "current_mesh", "default_mesh", "use_mesh",
           "data_parallel_spec", "mesh_signature", "replicated",
           "PartitionSpec", "NamedSharding", "Mesh"]

_mesh_stack = []


def make_mesh(axes=None, devices=None) -> Mesh:
    """Build a named device mesh.

    ``axes`` is an ordered mapping / list of (name, size) pairs; a size of
    ``-1`` absorbs the remaining devices (like a reshape). Default: all
    visible devices on one ``data`` axis — the reference's default
    data-parallel layout (``ctx=[mx.gpu(i) for i in ...]``,
    ref: python/mxnet/module/executor_group.py DataParallelExecutorGroup).
    """
    if devices is None:
        from ..diagnostics import guard
        devices = guard.devices()
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    if isinstance(axes, dict):
        items = list(axes.items())
    else:
        items = [(k, v) for k, v in axes]
    names = [k for k, _ in items]
    sizes = [v for _, v in items]
    n_fixed = math.prod(s for s in sizes if s != -1)
    for i, s in enumerate(sizes):
        if s == -1:
            sizes[i] = n // n_fixed
    if math.prod(sizes) != n:
        raise MXNetError(
            f"mesh axes {dict(zip(names, sizes))} do not tile the "
            f"{n} visible devices")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def default_mesh() -> Mesh:
    return make_mesh()


def current_mesh() -> Mesh:
    """The innermost ``use_mesh`` scope, or a fresh all-``data`` mesh."""
    if _mesh_stack:
        return _mesh_stack[-1]
    return default_mesh()


@contextmanager
def use_mesh(mesh: Mesh):
    """Scope a mesh as the framework-wide default (analog of the reference's
    kvstore-type selection picking the comm topology)."""
    _mesh_stack.append(mesh)
    try:
        yield mesh
    finally:
        _mesh_stack.pop()


def data_parallel_spec(mesh: Mesh, ndim: int, batch_axis: int = 0):
    """PartitionSpec sharding ``batch_axis`` over every data-like mesh axis
    present (``data`` and, if defined, ``pipe``-free batch splitting)."""
    spec = [None] * ndim
    if "data" in mesh.axis_names:
        spec[batch_axis] = "data"
    return PartitionSpec(*spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def mesh_signature(mesh: Mesh) -> dict:
    """JSON-able identity of a mesh — device count + axis sizes — for
    journal records and checkpoint metadata (the elastic tier logs the
    before/after shapes of a survivor rebuild, docs/elastic.md)."""
    return {"devices": int(mesh.devices.size),
            "axes": {name: int(mesh.shape[name])
                     for name in mesh.axis_names}}

"""Shared checkpoint file machinery for the sharded trainers
(ShardedTrainer, PipelinedTrainer — SURVEY §5.4 lifted to GSPMD state).

Layout: a ``.params``-format container (readable by ``nd.load``) with a
JSON ``__meta__`` entry. Single-process saves write one file; multi-host
saves write one ``.shard<rank>`` file per process holding only
locally-owned shards (entry key ``<name>|<index>``), plus a rank-0 meta
file, with group barriers so no reader sees a half-written set.

Crash consistency: every file lands via ``nd.save``'s atomic path, and
the directory-level commit protocol (:func:`commit_checkpoint` /
:func:`restore_checkpoint`, built on ``resilience.commit``) stages a
whole multi-file step under ``step-N.tmp/``, publishes it behind a
rank-0 MANIFEST + rename commit point, maintains a ``latest`` pointer
and keep-last-k retention, and restores from the newest step that
passes CRC validation — journaling every corrupt candidate it skips
(docs/checkpointing.md)."""
from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from .. import ndarray as nd
from ..base import MXNetError
from ..diagnostics.journal import get_journal
from ..resilience import commit as _commit

CKPT_FORMAT = 1


# ---------------------------------------------------------------------------
# Group abstraction: every cross-process decision in this module (who is
# rank 0, how many shard files, barrier, agree-on-an-int) goes through
# ONE pluggable object. The default is the jax.distributed world —
# existing behavior bit-for-bit. The elastic tier installs a
# cohort-backed group (mxnet_tpu.elastic.CohortGroup) whose barriers are
# deadline-bounded against the membership ledger, so a checkpoint commit
# can never hang on a dead rank (docs/elastic.md).
# ---------------------------------------------------------------------------

class JaxGroup:
    """The static jax.distributed world (identity single-process)."""

    kind = "jax"

    def index(self):
        return jax.process_index()

    def count(self):
        return jax.process_count()

    def barrier(self, tag):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"mxtpu_ckpt_{tag}")

    def bcast_int(self, value):
        """Rank 0's integer, agreed group-wide (identity single-process).
        Validation choices MUST be made once and shared: per-rank
        re-validation would both diverge on a corrupt candidate and
        stream every shard of every candidate through every process
        (O(world^2) reads of the shared filesystem)."""
        if jax.process_count() == 1:
            return int(value)
        from jax.experimental import multihost_utils
        return int(np.asarray(multihost_utils.broadcast_one_to_all(
            np.asarray(int(value), dtype=np.int64))))

    def owns_piece(self, position):
        """jax already partitions pieces by shard addressability — every
        addressable replica-0 piece is this process's to write."""
        return True

    def meta(self):
        return {"world": self.count()}


_JAX_GROUP = JaxGroup()
_group = None


def group():
    return _group if _group is not None else _JAX_GROUP


def set_group(g):
    """Install (or, with None, remove) the process-wide checkpoint
    group; returns the previous value so drivers can nest/restore."""
    global _group
    prev = _group
    _group = g
    return prev


def barrier(tag):
    """Group-wide sync; no-op single-process."""
    group().barrier(tag)


def gather_host(arr):
    """Device array -> numpy with exact bytes; gathers non-addressable
    shards over DCN in multi-host runs (full-file mode only)."""
    arr = jnp.asarray(arr)
    if arr.is_fully_addressable:
        return np.asarray(arr)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def idx_key(idx, shape):
    """Normalize a shard index (tuple of slices) to a stable string."""
    parts = []
    for sl, dim in zip(idx, shape):
        start, stop, _ = sl.indices(dim)
        parts.append(f"{start}:{stop}")
    return ",".join(parts)


def write_entries(fname, entries, meta):
    """Write placed arrays + meta. Full mode: collective gather on all
    processes, ONE writer (rank 0 — concurrent writes to a shared path
    would tear the file). Per-shard mode: rank-0 meta file + one
    ``.shard<rank>`` file per process."""
    g = group()
    meta_nd = {"__meta__": nd.NDArray(np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8).copy())}
    if not meta["per_shard"]:
        full = dict(meta_nd)
        for name, arr in entries.items():
            host = gather_host(arr)        # collective: every process
            if g.index() == 0:
                full[name] = nd.NDArray(host, _skip_device_put=True)
        if g.index() == 0:
            nd.save(fname, full)
        barrier("save_full")
        return
    if g.index() == 0:
        nd.save(fname, meta_nd)
    shard_entries = {}
    for name, arr in entries.items():
        arr = jnp.asarray(arr)
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue
            key = f"{name}|{idx_key(shard.index, arr.shape)}"
            if key not in shard_entries:
                shard_entries[key] = nd.NDArray(
                    np.asarray(shard.data), _skip_device_put=True)
    # cohort groups partition piece ownership round-robin over the SAME
    # sorted key sequence on every member (the cohort replicates the
    # global tree, so the sequences agree) — shard files stay disjoint
    # and per-rank write volume stays one share, exactly like the
    # addressability split in a real multi-host world
    shard_entries = {k: shard_entries[k]
                     for i, k in enumerate(sorted(shard_entries))
                     if g.owns_piece(i)}
    nd.save(f"{fname}.shard{g.index()}", shard_entries)
    barrier("save_shards")


def read_meta(fname):
    loaded = nd.load(fname)
    if "__meta__" not in loaded:
        raise MXNetError(
            f"{fname}: not a sharded-trainer checkpoint (no __meta__ "
            "entry); eager gluon.Trainer states use Trainer.load_states")
    meta = json.loads(bytes(loaded["__meta__"].asnumpy()).decode())
    if meta.get("format") != CKPT_FORMAT:
        raise MXNetError(f"{fname}: unsupported checkpoint format "
                         f"{meta.get('format')!r}")
    return meta, loaded


def needed_piece_keys(entries):
    """The (name, idxkey) pairs THIS process's addressable shards need —
    bounds per-shard load memory to one host's share of the checkpoint."""
    needed = set()
    for name, arr in entries.items():
        arr = jnp.asarray(arr)
        for shard in arr.addressable_shards:
            needed.add((name, idx_key(shard.index, arr.shape)))
    return needed


def read_pieces(fname, n_files, needed):
    """Collect per-shard entries from exactly the ``.shard0..N-1`` files
    the saving run wrote (N from meta — globbing would mix in stale
    files from an older save with a different process count)."""
    barrier("load_shards")     # writers must be done before reading
    pieces = {}
    for rank in range(n_files):
        path = f"{fname}.shard{rank}"
        if not os.path.exists(path):
            raise MXNetError(
                f"per-shard checkpoint incomplete: {path} missing "
                f"(meta says {n_files} shard files)")
        loaded = nd.load(path)
        if not isinstance(loaded, dict):
            # an EMPTY shard container (zero-state optimizer, or a
            # piece split that left this rank nothing) loads as a list
            continue
        for key, arr in loaded.items():
            name, ik = key.rsplit("|", 1)
            if (name, ik) in needed:
                pieces.setdefault(name, {})[ik] = arr.asnumpy()
    return pieces


def place_like(name, cur, loaded, pieces):
    """Rebuild one sharded array in ``cur``'s exact layout from either the
    full-file entries or the per-shard piece map (validating shape and
    dtype either way)."""
    cur = jnp.asarray(cur)
    if pieces is None:
        if name not in loaded:
            raise MXNetError(f"checkpoint is missing entry {name!r}")
        host = loaded[name].asnumpy()
        if tuple(host.shape) != tuple(cur.shape) or \
                jnp.dtype(host.dtype) != cur.dtype:
            raise MXNetError(
                f"checkpoint entry {name!r} is {host.dtype}{host.shape}, "
                f"expected {cur.dtype}{tuple(cur.shape)} — architecture "
                "or master_dtype mismatch")
        return jax.device_put(host, cur.sharding)
    per = pieces.get(name)
    if per is None:
        raise MXNetError(f"per-shard checkpoint is missing {name!r}")

    def cb(idx):
        piece = per.get(idx_key(idx, cur.shape))
        if piece is None:
            raise MXNetError(
                f"{name!r}: no saved piece for shard {idx} — mesh or "
                "sharding layout changed since save")
        if jnp.dtype(piece.dtype) != cur.dtype:
            raise MXNetError(
                f"checkpoint piece {name!r} is {piece.dtype}, expected "
                f"{cur.dtype} — master_dtype mismatch")
        return piece
    return jax.make_array_from_callback(cur.shape, cur.sharding, cb)


def rng_meta():
    from .. import _rng
    data, impl = _rng.get_state()
    return {"rng_impl": impl,
            "rng_data": [int(v) for v in np.ravel(data)],
            "rng_shape": list(data.shape)}


def restore_rng(meta):
    from .. import _rng
    data = np.asarray(meta["rng_data"], dtype=np.uint32).reshape(
        meta["rng_shape"])
    _rng.set_state(data, meta["rng_impl"])


# -- directory commit protocol (resilience.commit glued to the trainer
#    save/load callbacks; the crash-matrix tests drive commit directly) -----

CKPT_BASENAME = "ckpt"


def _bcast_int(value):
    """Rank 0's integer, agreed group-wide — see JaxGroup.bcast_int for
    why validation choices must be made once and shared."""
    return group().bcast_int(value)


def commit_checkpoint(root, step, save_cb, keep_last=None):
    """Commit-protocol save: ``save_cb(prefix)`` stages this process's
    files (the existing save_checkpoint/save_states writers, untouched)
    under ``<root>/step-N.tmp/``; after a group barrier rank 0 writes
    the CRC manifest, publishes the step with one rename, moves the
    ``latest`` pointer, and applies keep-last-k retention."""
    g = group()
    step = int(step)
    already = False
    if g.index() == 0:
        try:
            _commit.validate_step(root, step)
            already = True       # e.g. restore -> immediate re-checkpoint
        except ValueError:
            pass
    if _bcast_int(already):
        # same step number = same trainer state (step is the update
        # count): re-publishing would only re-rename an identical dir
        get_journal().event("ckpt_skip_existing", root=root, step=step)
        return step
    if g.index() == 0:
        _commit.prepare_stage(root, step)
    barrier("ckpt_stage")
    save_cb(os.path.join(_commit.stage_dir(root, step), CKPT_BASENAME))
    barrier("ckpt_staged")
    if g.index() == 0:
        _commit.finalize(root, step, keep_last=keep_last, meta=g.meta())
        get_journal().event("ckpt_committed", root=root, step=step)
    barrier("ckpt_committed")
    return step


_NO_VALID, _PINNED_BAD = -1, -2


def restore_checkpoint(root, load_cb, step=None):
    """Resume from ``root``: with ``step`` pinned, that step must
    validate; otherwise the newest valid committed step wins, and every
    corrupt/torn candidate skipped on the way down is journaled as
    ``ckpt_fallback`` (never a silent skip, never an exception escape
    for a *recoverable* root).

    CRC validation (which streams every candidate's files) runs on rank
    0 only; the chosen step is broadcast so the group restores the same
    step without each process re-reading every shard of every
    candidate."""
    def _skip(s, reason):
        get_journal().event("ckpt_fallback", root=root, step=s,
                            detail=reason[:300])

    found = _NO_VALID
    pinned_err = ""
    if group().index() == 0:
        if step is not None:
            try:
                _commit.validate_step(root, int(step))
                found = int(step)
            except ValueError as e:
                found, pinned_err = _PINNED_BAD, str(e)
        else:
            got = _commit.find_restorable(root, on_skip=_skip)
            if got is not None:
                found = got[0]
    found = _bcast_int(found)
    if found == _PINNED_BAD:
        raise MXNetError(f"checkpoint step {step} under {root!r} failed "
                         f"validation: {pinned_err or 'see rank 0'}")
    if found == _NO_VALID:
        raise MXNetError(
            f"no valid committed checkpoint under {root!r} — nothing "
            "to restore (uncommitted step-*.tmp staging dirs and "
            "corrupt steps are ignored)")
    load_cb(os.path.join(_commit.step_dir(root, found), CKPT_BASENAME))
    get_journal().event("ckpt_restored", root=root, step=found)
    return found

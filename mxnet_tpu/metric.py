"""Evaluation metrics (ref: python/mxnet/metric.py).

Registry + the full EvalMetric family the reference training loops consume
(`Module.fit(eval_metric=...)`, user Gluon loops). Updates pull data to host
(numpy) like the reference — metric update is the loop's sync point
(SURVEY §3.2)."""
from __future__ import annotations

import numpy as np

from .base import MXNetError

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
           "Perplexity", "PearsonCorrelation", "Loss", "Torch", "Caffe",
           "CustomMetric", "LatencySummary", "create", "register", "np_metric",
           # attached by the package init from metric_det (detection mAP)
           "VOCMApMetric", "VOC07MApMetric"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def _alias(name, klass):
    _REGISTRY[name.lower()] = klass


def create(metric, *args, **kwargs):
    """ref: mx.metric.create — name / callable / list / instance."""
    if callable(metric) and not isinstance(metric, type):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        if metric.lower() not in _REGISTRY:
            raise MXNetError(f"unknown metric {metric!r}; known: "
                             f"{sorted(_REGISTRY)}")
        return _REGISTRY[metric.lower()](*args, **kwargs)
    if isinstance(metric, type) and issubclass(metric, EvalMetric):
        return metric(*args, **kwargs)
    raise MXNetError(f"cannot create metric from {metric!r}")


def _to_numpy(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return np.asarray(x)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class EvalMetric:
    """Base metric (ref: metric.py EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


class CompositeEvalMetric(EvalMetric):
    """ref: metric.py CompositeEvalMetric."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.extend(_as_list(name))
            values.extend(_as_list(value))
        return (names, values)


@register
class Accuracy(EvalMetric):
    """ref: metric.py Accuracy."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label)
            if pred.ndim > label.ndim:
                pred = np.argmax(pred, axis=self.axis)
            pred = pred.astype(np.int64).ravel()
            label = label.astype(np.int64).ravel()
            self.sum_metric += int((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    """ref: metric.py TopKAccuracy."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(f"{name}_{top_k}", output_names, label_names)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(np.int64).ravel()
            top = np.argpartition(pred, -self.top_k, axis=-1)[..., -self.top_k:]
            top = top.reshape(len(label), -1)
            self.sum_metric += int((top == label[:, None]).any(axis=1).sum())
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    """Binary F1 (ref: metric.py F1)."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        super().__init__(name, output_names, label_names)

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype(np.int64)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = np.argmax(pred, axis=-1)
            else:
                pred = (pred.ravel() > 0.5).astype(np.int64)
            pred = pred.ravel()
            self._tp += int(((pred == 1) & (label == 1)).sum())
            self._fp += int(((pred == 1) & (label == 0)).sum())
            self._fn += int(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)

    def get(self):
        precision = self._tp / max(self._tp + self._fp, 1)
        recall = self._tp / max(self._tp + self._fn, 1)
        f1 = 2 * precision * recall / max(precision + recall, 1e-12)
        return (self.name, f1 if self.num_inst else float("nan"))


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (ref: metric.py MCC)."""

    def __init__(self, name="mcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def reset(self):
        super().reset()
        self._tp = self._fp = self._tn = self._fn = 0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype(np.int64)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = np.argmax(pred, axis=-1)
            else:
                pred = (pred.ravel() > 0.5).astype(np.int64)
            pred = pred.ravel()
            self._tp += int(((pred == 1) & (label == 1)).sum())
            self._fp += int(((pred == 1) & (label == 0)).sum())
            self._tn += int(((pred == 0) & (label == 0)).sum())
            self._fn += int(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)

    def get(self):
        tp, fp, tn, fn = self._tp, self._fp, self._tn, self._fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        mcc = (tp * tn - fp * fn) / denom if denom else 0.0
        return (self.name, mcc if self.num_inst else float("nan"))


@register
class MAE(EvalMetric):
    """ref: metric.py MAE."""

    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred).reshape(label.shape)
            self.sum_metric += float(np.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    """ref: metric.py MSE."""

    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred).reshape(label.shape)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    """ref: metric.py RMSE."""

    def __init__(self, name="rmse", output_names=None, label_names=None):
        EvalMetric.__init__(self, name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(np.sqrt(self.sum_metric / self.num_inst)))


@register
class CrossEntropy(EvalMetric):
    """ref: metric.py CrossEntropy — pred rows are probabilities."""

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).ravel().astype(np.int64)
            pred = _to_numpy(pred).reshape(len(label), -1)
            prob = pred[np.arange(len(label)), label]
            self.sum_metric += float(-np.log(prob + self.eps).sum())
            self.num_inst += len(label)


@register
class NegativeLogLikelihood(CrossEntropy):
    """ref: metric.py NegativeLogLikelihood."""

    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class Perplexity(CrossEntropy):
    """ref: metric.py Perplexity."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name=name, output_names=output_names,
                         label_names=label_names)
        self.ignore_label = ignore_label

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).ravel().astype(np.int64)
            pred = _to_numpy(pred).reshape(len(label), -1)
            prob = pred[np.arange(len(label)), label]
            if self.ignore_label is not None:
                keep = label != self.ignore_label
                prob = prob[keep]
            self.sum_metric += float(-np.log(prob + self.eps).sum())
            self.num_inst += len(prob)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(np.exp(self.sum_metric / self.num_inst)))


@register
class PearsonCorrelation(EvalMetric):
    """ref: metric.py PearsonCorrelation."""

    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._labels.append(_to_numpy(label).ravel())
            self._preds.append(_to_numpy(pred).ravel())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return (self.name, float("nan"))
        label = np.concatenate(self._labels)
        pred = np.concatenate(self._preds)
        return (self.name, float(np.corrcoef(label, pred)[0, 1]))


@register
class Loss(EvalMetric):
    """Mean of raw loss outputs (ref: metric.py Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = _to_numpy(pred)
            self.sum_metric += float(loss.sum())
            self.num_inst += loss.size


class Torch(Loss):
    """ref: metric.py Torch (alias of Loss)."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class Caffe(Loss):
    """ref: metric.py Caffe (alias of Loss)."""

    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wrap feval(label, pred) (ref: metric.py CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__(f"custom({name})", output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            value = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(value, tuple):
                sum_metric, num_inst = value
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += value
                self.num_inst += 1


def np_metric(numpy_feval=None, name=None, allow_extra_outputs=False):
    """Decorator form (ref: metric.py np)."""
    def deco(feval):
        def factory():
            return CustomMetric(feval, name or feval.__name__,
                                allow_extra_outputs)
        return factory
    if numpy_feval is not None:
        return deco(numpy_feval)
    return deco


# LatencySummary moved to observability.metrics (the metrics registry's
# histogram backend — docs/observability.md); re-exported here for
# compatibility with every existing consumer (serving, bench, tests).
from .observability.metrics import LatencySummary  # noqa: E402


_alias("ce", CrossEntropy)
_alias("nll_loss", NegativeLogLikelihood)
_alias("acc", Accuracy)
_alias("top_k_acc", TopKAccuracy)
_alias("top_k_accuracy", TopKAccuracy)
_alias("pearson_correlation", PearsonCorrelation)

"""Fault injection for the checkpoint stack — the chaos layer that
*proves* crash consistency instead of asserting it.

``resilience.atomic`` consults a process-wide hook at every phase of a
durable write (``open`` → ``write``@N-bytes → ``fsync`` → ``replace``
→ ``after_replace`` → ``dir_fsync``) and the commit protocol adds its
own points (``publish``, ``gc``). This module installs rules on that
hook:

- :func:`crash` raises :class:`SimulatedCrash` (a ``BaseException``,
  like ``KeyboardInterrupt``): retry layers must NOT absorb it, and
  ``atomic_write`` leaves the torn temp file on disk exactly as a
  killed process would.
- :func:`io_error` raises :class:`FaultError` (an ``OSError``): the
  bounded-retry path IS expected to absorb ``times <= retries`` of
  these.
- :func:`sigterm` delivers a real SIGTERM to this process — the
  preemption drill (install ``resilience.preempt`` first!).

Cookbook (docs/checkpointing.md has more)::

    from mxnet_tpu.testing import faults
    with faults.inject(faults.crash("replace")):
        with pytest.raises(faults.SimulatedCrash):
            nd.save(path, new_params)      # killed at the commit edge
    nd.load(path)                          # still the OLD file, intact

The crash matrix iterates :data:`CRASH_POINTS` ×
:func:`write_offsets`, killing the writer at every phase and asserting
a reader always sees the old or the new checkpoint, fully intact.
"""
from __future__ import annotations

import contextlib
import errno
import os
import signal
import threading
import time

from ..resilience import atomic

__all__ = ["CRASH_POINTS", "DiskBudget", "DiskFullError", "FaultError",
           "FaultPlan", "FaultRule", "FdExhaustError", "PoisonError",
           "PoisonSchedule", "SimulatedCrash", "corrupt_params", "crash",
           "disk_budget", "disk_full", "fd_exhaust", "inject", "io_error",
           "partition", "poison_batch", "poison_grads", "regress_params",
           "sigkill", "sigterm", "slow_call", "slow_canary",
           "tenant_poison", "torn_heartbeat", "write_offsets"]

# every phase of one atomic file write, in order — plus the commit
# protocol's own points (publish = the step-dir rename commit point)
CRASH_POINTS = ("open", "write", "fsync", "replace", "after_replace",
                "dir_fsync", "publish", "gc")


class SimulatedCrash(BaseException):
    """Process-death stand-in. Deliberately NOT an Exception: retry
    helpers and cleanup paths must let it fly, mirroring a kill."""

    def __init__(self, point, path, nbytes=None):
        super().__init__(f"simulated crash at {point} ({path}"
                         + (f", {nbytes}B written)" if nbytes is not None
                            else ")"))
        self.point = point
        self.path = path
        self.nbytes = nbytes


class FaultError(OSError):
    """Injected transient I/O failure (EIO): the retry path's food."""

    def __init__(self, point, path):
        super().__init__(5, f"injected I/O error at {point}", path)
        self.point = point


class DiskFullError(OSError):
    """Injected ENOSPC: the resource-exhaustion shape retries cannot
    fix — ``resilience.retry`` classifies it fail-fast (freeing space
    is the remedy, not patience)."""

    def __init__(self, point, path):
        super().__init__(errno.ENOSPC,
                         f"injected disk full at {point}", path)
        self.point = point


class FdExhaustError(OSError):
    """Injected EMFILE at a descriptor-allocating site (file open,
    socket connect): the fd-starvation shape a leaked-handle bug
    produces in production."""

    def __init__(self, point, path):
        super().__init__(errno.EMFILE,
                         f"injected fd exhaustion at {point}", path)
        self.point = point


class PoisonError(RuntimeError):
    """Injected NON-transient predictor failure: deliberately not an
    OSError, so the serving transient-retry path must NOT absorb it —
    it feeds a fleet tenant's breaker instead (docs/serving.md)."""

    def __init__(self, point, path):
        super().__init__(f"injected predictor poison at {point} ({path})")
        self.point = point
        self.path = path


class FaultRule:
    """One trigger: when ``point`` (and optional path substring /
    cumulative-byte threshold) matches, raise ``exc_factory`` — or,
    for non-failing faults (injected latency, torn-file surgery), run
    ``action`` instead. Fires at most ``times`` times (None = always)."""

    def __init__(self, point, exc_factory, path_part=None,
                 after_bytes=None, times=None, action=None):
        self.point = point
        self.exc_factory = exc_factory
        self.path_part = path_part
        self.after_bytes = after_bytes
        self.times = times
        self.action = action
        self.fired = 0

    def matches(self, point, path, nbytes, size):
        if point != self.point:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.path_part is not None and self.path_part not in (path or ""):
            return False
        if self.after_bytes is not None:
            # fire on the chunk that would carry the file PAST the
            # threshold (kill granularity is per-write: the file is left
            # a <= after_bytes prefix, a real truncation shape)
            if nbytes is None or nbytes + (size or 0) <= self.after_bytes:
                return False
        return True

    def fire(self, point, path, nbytes):
        self.fired += 1
        if self.exc_factory is None:
            self.action(point, path, nbytes)
            return
        raise self.exc_factory(point, path, nbytes)


def crash(point, path_part=None, after_bytes=None, times=1) -> FaultRule:
    """Kill the writer at ``point`` (``after_bytes`` arms the ``write``
    point once that many bytes hit the temp file)."""
    if point == "write" and after_bytes is None:
        after_bytes = 0
    return FaultRule(point, lambda p, f, n: SimulatedCrash(p, f, n),
                     path_part=path_part, after_bytes=after_bytes,
                     times=times)


def io_error(point, path_part=None, times=1) -> FaultRule:
    """Transient EIO at ``point``, ``times`` times then clean."""
    return FaultRule(point, lambda p, f, n: FaultError(p, f),
                     path_part=path_part, times=times)


def slow_call(site, delay_s, path_part=None, times=None) -> FaultRule:
    """Inject ``delay_s`` of latency at a named trip site (e.g. the
    server's ``serving_predict``, the pool router's ``router_attempt``
    whose path carries the replica id, or the fleet's ``serving_tenant``
    whose path carries the tenant name — ``path_part`` targets one
    replica/tenant). Nothing fails, everything is just late: the
    slow-replica chaos shape that tail-latency hedging and circuit
    breakers must route around (docs/serving.md failure matrix)."""
    return FaultRule(site, None, path_part=path_part, times=times,
                     action=lambda p, f, n: time.sleep(delay_s))


def tenant_poison(tenant, times=None) -> FaultRule:
    """Poison ONE fleet tenant's predictor: the ``serving_tenant`` trip
    site (serving/fleet.py — its path is the tenant name) raises a
    non-transient :class:`PoisonError` whenever ``tenant``'s batch
    executes.  The per-tenant isolation drill: the poisoned tenant must
    quarantine itself (``TenantQuarantined`` after the breaker
    threshold) while every other tenant's p99 stays put.  Composes
    with ``slow_call``/``io_error`` at the same site for per-tenant
    latency/transient injection."""
    return FaultRule("serving_tenant", lambda p, f, n: PoisonError(p, f),
                     path_part=str(tenant), times=times)


def corrupt_params(root, step, params_file=None, flip_at=None):
    """Bit-flip a COMMITTED parameter shard post-CRC-manifest: the
    committed step dir's ``.params`` payload (or ``params_file``) gets
    one byte XOR'd in place, manifest untouched — the silent-storage-
    rot shape (cosmic ray, firmware bug, torn RAID rebuild) that only
    CRC validation can catch.  ``resilience.commit.validate_step`` must
    now fail the step and a serving ``ParamStore`` must skip it
    (``ckpt_fallback``), feeding the owning tenant's breaker in a
    fleet.  Returns the corrupted file's path."""
    from ..resilience import commit as _commit
    d = _commit.step_dir(root, step)
    if params_file is None:
        names = sorted(f for f in os.listdir(d) if f.endswith(".params"))
        if not names:
            raise ValueError(f"no .params payload in {d}")
        params_file = names[0]
    path = os.path.join(d, params_file)
    with open(path, "r+b") as f:
        data = f.read()
        if not data:
            raise ValueError(f"{path} is empty; nothing to corrupt")
        at = len(data) // 2 if flip_at is None else int(flip_at)
        f.seek(at)
        f.write(bytes([data[at] ^ 0xFF]))
    return path


def regress_params(root, step, scale=10.0, params_file=None):
    """Systematically skew a COMMITTED step's weights and RE-MANIFEST
    it, so CRC validation PASSES while every output is wrong-but-finite
    — the silent model regression (bad training run, mis-exported
    quantization, wrong branch promoted) that no storage checksum can
    catch.  The counterpart of :func:`corrupt_params`: that one leaves
    the manifest stale so the CRC gate rejects the step; this one is
    indistinguishable from a healthy checkpoint until you LOOK AT THE
    ANSWERS, which is exactly what the deploy controller's mirrored
    parity gate does (docs/serving.md, canary deployment).  Every
    ``.params`` array is multiplied by ``scale``; returns the skewed
    file's path."""
    import numpy as np
    from .. import ndarray as nd
    from ..resilience import commit as _commit
    d = _commit.step_dir(root, step)
    manifest = _commit.read_manifest(d)
    if params_file is None:
        names = sorted(f for f in manifest["files"]
                       if f.endswith(".params"))
        if not names:
            raise ValueError(f"no .params payload in {d}")
        params_file = names[0]
    path = os.path.join(d, params_file)
    loaded = nd.load(path)
    skewed = {k: nd.array(np.asarray(v.asnumpy()) * float(scale))
              for k, v in loaded.items()}
    nd.save(path, skewed)
    # refresh the CRCs over the skewed payload: the step stays fully
    # commit-protocol-valid — the whole point of this fault shape
    _commit.write_manifest(d, step, manifest.get("meta") or {})
    return path


def slow_canary(delay_s, replica=None, times=None) -> FaultRule:
    """Inject ``delay_s`` of latency at the ``deploy_canary`` trip site
    (serving/router.py): every canary-bound dispatch — live traffic
    routed to a canary replica AND mirrored parity probes — during a
    deployment, optionally narrowed to one ``replica`` id.  Control
    traffic is untouched, so the deploy p99 gate sees a clean
    canary-vs-control latency split: the slow-canary chaos shape that
    must roll back on the p99 delta, distinctly from a numerically bad
    canary (:func:`regress_params`)."""
    return FaultRule("deploy_canary", None,
                     path_part=None if replica is None else str(replica),
                     times=times,
                     action=lambda p, f, n: time.sleep(delay_s))


def torn_heartbeat(path_part="hb/", keep_bytes=7, times=1) -> FaultRule:
    """Tear the next matching heartbeat publish: truncate the staged
    temp file to ``keep_bytes`` just before the rename lands, so the
    seq file holds a partial JSON prefix — the shape a non-atomic
    writer, a full disk, or a dying NFS client produces. Liveness
    readers must degrade (the member reads as stale until a whole
    record lands) and never crash (docs/elastic.md)."""
    def _tear(point, path, nbytes):
        import glob as _glob
        # staged temps are per-call unique (<path>.tmp.<pid>.<n>):
        # tear whichever is in flight for this path
        pattern = _glob.escape(f"{path}{atomic._TMP_MARK}") + "*"
        for tmp in _glob.glob(pattern):
            try:
                with open(tmp, "r+b") as f:
                    f.truncate(int(keep_bytes))
            except OSError:
                pass             # no temp staged: nothing to tear
    return FaultRule("replace", None, path_part=path_part, times=times,
                     action=_tear)


# -- resource exhaustion (the chaos conductor's new family) -----------------

def disk_full(point="write", path_part=None, after_bytes=None,
              times=None) -> FaultRule:
    """ENOSPC at one durable-write trip point (``write`` fires on the
    chunk that would carry the file past ``after_bytes``; ``fsync`` /
    ``replace`` model a filesystem that only discovers exhaustion at
    the flush/rename edge).  Unlike :func:`io_error`'s EIO, the retry
    layer must NOT absorb this — it fails fast, cleans the staged temp,
    and journals one deduped ``disk_full`` record per path."""
    return FaultRule(point, lambda p, f, n: DiskFullError(p, f),
                     path_part=path_part, after_bytes=after_bytes,
                     times=times)


class DiskBudget:
    """One shrinking free-space budget shared by EVERY durable writer —
    the budget-mode ``disk_full``.  Each staged ``write`` draws its byte
    count down; once the budget is exhausted, all matched write phases
    raise ENOSPC until :meth:`heal` refills it.  This is the composed
    production shape (journals, flight dumps, checkpoint commits, AOT
    store, tuned tables all competing for the same full disk), which
    single-point injection cannot reproduce."""

    def __init__(self, free_bytes):
        self.free = int(free_bytes)
        self._lock = threading.Lock()

    def draw(self, size) -> bool:
        """Charge ``size`` staged bytes; True once the budget is gone."""
        with self._lock:
            self.free -= int(size or 0)
            return self.free < 0

    def exhausted(self) -> bool:
        with self._lock:
            return self.free < 0

    def heal(self, free_bytes) -> None:
        """Refill (space was freed): writers succeed again."""
        with self._lock:
            self.free = int(free_bytes)


class _BudgetRule(FaultRule):
    """Budget-mode rule: matches any durable-write phase once the shared
    :class:`DiskBudget` runs dry (``write`` phases charge it first)."""

    _POINTS = ("open", "write", "fsync", "replace")

    def __init__(self, budget, path_part=None):
        super().__init__(None, lambda p, f, n: DiskFullError(p, f),
                         path_part=path_part)
        self.budget = budget

    def matches(self, point, path, nbytes, size):
        if point not in self._POINTS:
            return False
        if self.path_part is not None and self.path_part not in (path or ""):
            return False
        if point == "write":
            return self.budget.draw(size)
        return self.budget.exhausted()


def disk_budget(free_bytes, path_part=None) -> _BudgetRule:
    """Budget-mode disk_full: one rule whose shared :class:`DiskBudget`
    (exposed as ``rule.budget``) every durable writer draws down —
    whichever writer lands the exhausting byte trips, and every later
    durable phase keeps tripping until ``rule.budget.heal(n)``."""
    return _BudgetRule(DiskBudget(free_bytes), path_part=path_part)


def fd_exhaust(site="open", path_part=None, times=None) -> FaultRule:
    """EMFILE at a descriptor-allocating trip site: the atomic-write
    ``open`` point, or the pool client's ``wire_connect`` socket-open
    site (its path carries the replica id).  Consumers must surface a
    structured degrade — never hang or corrupt — because no retry
    budget can conjure descriptors back."""
    return FaultRule(site, lambda p, f, n: FdExhaustError(p, f),
                     path_part=path_part, times=times)


def partition(peer=None, stall_s=1.0, site="wire_send",
              times=1) -> FaultRule:
    """Wire-level partition: frames to the matched ``peer`` stall
    ``stall_s`` — past the socket timeout the caller budgeted — then
    the link heals (``times`` bounds the partition window).  ``site``
    is ``wire_send`` (ProcReplica's frame-send seam, path = replica id)
    by default; an in-process pool partitions at ``router_attempt``
    instead.  The router must see a bounded structured timeout and
    reroute, exactly as for a dead peer — except this peer comes back."""
    return FaultRule(site, None,
                     path_part=None if peer is None else str(peer),
                     times=times,
                     action=lambda p, f, n: time.sleep(float(stall_s)))


class FaultPlan:
    """The installed hook: first matching rule fires; every firing is
    recorded in ``log`` for assertions."""

    def __init__(self, *rules):
        self.rules = list(rules)
        self.log = []

    def __call__(self, point, path=None, nbytes=None, size=None):
        for rule in self.rules:
            if rule.matches(point, path, nbytes, size):
                self.log.append((point, path, nbytes))
                rule.fire(point, path, nbytes)
                return


@contextlib.contextmanager
def inject(*rules):
    """Install a :class:`FaultPlan` for the duration; restores the
    previous hook (nestable) on exit."""
    plan = FaultPlan(*rules)
    prev = atomic.set_fault_hook(plan)
    try:
        yield plan
    finally:
        atomic.set_fault_hook(prev)


def sigterm() -> None:
    """Deliver a REAL SIGTERM to this process — the preemption drill.
    Only safe once ``resilience.preempt.install()`` holds the signal;
    otherwise this kills the interpreter, as in production."""
    os.kill(os.getpid(), signal.SIGTERM)


def sigkill() -> None:
    """SIGKILL this process — the "host vanished" shape: no handlers,
    no journal breadcrumb, no atexit. The elastic chaos tests kill a
    cohort rank with this to prove loss detection needs zero
    cooperation from the dying process (docs/elastic.md)."""
    os.kill(os.getpid(), signal.SIGKILL)


# -- numeric poison (the guardrails chaos layer, docs/guardrails.md) --------
# Two injection shapes mirror how bad numerics arrive in production:
#   * poison_batch — a corrupt INPUT (bad record, overflowed feature):
#     NaN/Inf flows through forward/backward naturally, so the fused
#     in-program guard is exercised end to end with no program changes;
#   * poison_grads — a corrupt GRADIENT buffer written directly (the
#     eager-trainer shape: fp16 overflow lands in the grad arrays).
# PoisonSchedule drives "poison at step N" / "persistent poison" chaos
# loops without every test reinventing the step bookkeeping.

def poison_batch(batch, value=float("nan"), index=0):
    """Copy a host batch with ``flat[index] = value`` (default NaN).
    The poisoned copy is a new float array — the caller's batch is
    untouched, so the same test can replay the clean batch after."""
    import numpy as np
    out = np.array(batch, copy=True)
    if not np.issubdtype(out.dtype, np.floating):
        out = out.astype(np.float32)
    out.reshape(-1)[index] = value
    return out


def poison_grads(params, value=float("nan"), index=0):
    """Write ``value`` into one element of the first live gradient
    buffer (eager gluon Trainer / Module shape). Returns the poisoned
    parameter's name; raises if nothing has a gradient."""
    for p in params:
        if getattr(p, "grad_req", "write") == "null":
            continue
        for g in (getattr(p, "_grad", None) or ()):
            if g is None:
                continue
            data = g._data
            if hasattr(data, "at"):           # jax.Array: functional set
                g._rebind(data.reshape(-1).at[index].set(value)
                          .reshape(data.shape))
            else:                             # numpy fallback
                flat = data.reshape(-1)
                flat[index] = value
            return p.name
    raise ValueError("poison_grads: no parameter with a gradient buffer")


class PoisonSchedule:
    """Which steps are poisoned: explicit ``at_steps`` and/or every step
    from ``persistent_from`` on. ``batch(step, x)`` returns the batch to
    feed — poisoned or clean — and records what it did in ``log``."""

    def __init__(self, at_steps=(), persistent_from=None,
                 value=float("nan")):
        self.at_steps = frozenset(int(s) for s in at_steps)
        self.persistent_from = persistent_from
        self.value = value
        self.log = []

    def poisoned(self, step) -> bool:
        hit = int(step) in self.at_steps or (
            self.persistent_from is not None
            and int(step) >= int(self.persistent_from))
        if hit:
            self.log.append(int(step))
        return hit

    def batch(self, step, x):
        return poison_batch(x, value=self.value) if self.poisoned(step) \
            else x


def write_offsets(total_bytes: int) -> list[int]:
    """Representative crash offsets for a payload of ``total_bytes``:
    before the first byte, inside the header, mid-payload, and just
    short of the end — the truncation shapes a real kill produces."""
    probes = {0, 1, min(15, total_bytes), total_bytes // 2,
              max(total_bytes - 1, 0)}
    return sorted(p for p in probes if 0 <= p < max(total_bytes, 1))

"""``mxnet_tpu.testing`` — test-support layers that ship with the
package (so downstream users can chaos-test their own checkpoint
integrations, not just ours). Currently: :mod:`.faults`, the
fault-injection harness behind the crash-matrix tests."""
from __future__ import annotations

from . import faults

__all__ = ["faults"]

"""Test utilities — port of the reference's test methodology
(ref: python/mxnet/test_utils.py): dtype-aware ``assert_almost_equal``,
central-finite-difference ``check_numeric_gradient``, and
``check_consistency`` across contexts (the reference's CPU-vs-GPU trick,
here CPU-jax vs TPU-jax / eager vs jit).
"""
from __future__ import annotations

import numpy as np

from . import _rng
from .base import _as_np_dtype
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "almost_equal", "same", "rand_ndarray", "rand_shape_2d",
           "rand_shape_3d", "rand_shape_nd", "check_numeric_gradient",
           "check_consistency", "default_dtype", "list_contexts"]

_default_ctx = [None]

# dtype-aware default tolerances (ref: test_utils.py assert_almost_equal)
_RTOL = {np.dtype(np.float16): 1e-2, np.dtype(np.float32): 1e-4,
         np.dtype(np.float64): 1e-6}
_ATOL = {np.dtype(np.float16): 1e-3, np.dtype(np.float32): 1e-5,
         np.dtype(np.float64): 1e-7}


def default_context() -> Context:
    return _default_ctx[0] or current_context()


def set_default_context(ctx: Context):
    _default_ctx[0] = ctx


def default_dtype():
    return np.float32


def list_contexts():
    ctxs = [cpu()]
    try:
        from .context import tpu, _accelerator_devices
        if _accelerator_devices():
            ctxs.append(tpu())
    except Exception:
        pass
    return ctxs


def _as_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b) -> bool:
    return np.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=None, atol=None) -> bool:
    a, b = _as_np(a), _as_np(b)
    rtol = rtol if rtol is not None else _RTOL.get(a.dtype, 1e-4)
    atol = atol if atol is not None else _ATOL.get(a.dtype, 1e-5)
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    a_np, b_np = _as_np(a).astype(np.float64), _as_np(b).astype(np.float64)
    rtol = rtol if rtol is not None else _RTOL.get(_as_np(a).dtype, 1e-4)
    atol = atol if atol is not None else _ATOL.get(_as_np(a).dtype, 1e-5)
    np.testing.assert_allclose(a_np, b_np, rtol=rtol, atol=atol,
                               err_msg=f"{names[0]} vs {names[1]}")


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None,
                 scale=1.0) -> NDArray:
    if stype != "default":
        raise NotImplementedError("sparse rand_ndarray not supported yet")
    arr = np.random.uniform(-scale, scale, size=shape)
    return array(arr.astype(_as_np_dtype(dtype or np.float32)), ctx=ctx)


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def numeric_grad(executor_fn, inputs, eps=1e-4):
    """Central finite differences d(sum(f))/d(inputs)
    (ref: test_utils.py numeric_grad)."""
    grads = []
    for i, x in enumerate(inputs):
        x_np = x.asnumpy().astype(np.float64)
        g = np.zeros_like(x_np)
        flat = x_np.ravel()
        gflat = g.ravel()
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            plus = float(np.sum(_as_np(executor_fn(
                [array(x_np.astype(np.float32)) if k == i else inputs[k]
                 for k in range(len(inputs))]))))
            flat[j] = orig - eps
            minus = float(np.sum(_as_np(executor_fn(
                [array(x_np.astype(np.float32)) if k == i else inputs[k]
                 for k in range(len(inputs))]))))
            flat[j] = orig
            gflat[j] = (plus - minus) / (2 * eps)
        grads.append(g)
    return grads


def check_numeric_gradient(fn, inputs, rtol=1e-2, atol=1e-3, eps=1e-3):
    """Compare autograd gradients of ``sum(fn(*inputs))`` against central
    finite differences (ref: mx.test_utils.check_numeric_gradient — the
    reference's primary per-op gradient test method, SURVEY §4)."""
    from . import autograd
    inputs = [x if isinstance(x, NDArray) else array(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
        loss = out.sum() if isinstance(out, NDArray) else sum(o.sum() for o in out)
    loss.backward()
    analytic = [x.grad.asnumpy() for x in inputs]

    def run(xs):
        # numeric pass must evaluate in the SAME mode the analytic pass
        # recorded under (train): pause() alone flips mode-dependent ops
        # (training BatchNorm) to inference and the comparison is then
        # between two different functions
        with autograd.pause(train_mode=True):
            out2 = fn(*xs)
        return out2 if isinstance(out2, NDArray) else out2[0] + sum(out2[1:], 0 * out2[0])

    numeric = numeric_grad(lambda xs: run(xs), inputs, eps=eps)
    for i, (a, n) in enumerate(zip(analytic, numeric)):
        np.testing.assert_allclose(a, n, rtol=rtol, atol=atol,
                                   err_msg=f"gradient mismatch on input {i}")


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-5):
    """Run ``fn`` on each context and check outputs agree — the reference's
    CPU-vs-GPU consistency harness (ref: tests/python/gpu/test_operator_gpu.py
    check_consistency), retargeted to CPU-jax vs accelerator-jax."""
    ctx_list = ctx_list or list_contexts()
    baseline = None
    for ctx in ctx_list:
        moved = [x.as_in_context(ctx) for x in inputs]
        out = fn(*moved)
        outs = out if isinstance(out, (list, tuple)) else [out]
        if baseline is None:
            baseline = [o.asnumpy() for o in outs]
        else:
            for b, o in zip(baseline, outs):
                np.testing.assert_allclose(b, o.asnumpy(), rtol=rtol, atol=atol,
                                           err_msg=f"inconsistent on {ctx}")
    return baseline

"""``mx.nd`` — the imperative operator namespace.

Like the reference, this namespace is **generated at import time from the op
registry** (ref: python/mxnet/ndarray/register.py, which synthesizes wrappers
from MXSymbolListAtomicSymbolCreators): every registered operator gets a
Python wrapper whose signature/docstring come from its OpParam spec, grouped
into the same sub-namespaces the reference has (``nd.random``, ``nd.linalg``,
``nd.contrib``, ``nd._internal``).
"""
from __future__ import annotations

import sys
import types

import jax
import numpy as _np

from .. import _dispatch
from ..ops import registry as _registry
from .ndarray import (NDArray, arange, array, concat, empty, eye, full,
                      imdecode, linspace, load, moveaxis, onehot_encode, ones,
                      save, stack, waitall, zeros)

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "eye", "linspace", "concat", "stack", "save", "load", "waitall",
           "random", "linalg", "contrib", "op", "_internal", "zeros_like",
           "ones_like", "moveaxis", "onehot_encode"]

_ARRAYLIKE = (NDArray, _np.ndarray, jax.Array, list)


def _make_wrapper(opname: str, op: _registry.Operator):
    param_order = [p.name for p in op.params]

    def wrapper(*args, out=None, name=None, **kwargs):
        args = list(args)
        if op.num_inputs == 0:
            inputs = []
        elif op.num_inputs == -1:
            inputs = []
            while args and isinstance(args[0], _ARRAYLIKE):
                inputs.append(args.pop(0))
        else:
            inputs, args = args[:op.num_inputs], args[op.num_inputs:]
        # remaining positionals map onto declared params in order
        for val, pname in zip(args, param_order):
            if pname in kwargs:
                raise TypeError(f"{opname}: got multiple values for {pname!r}")
            kwargs[pname] = val
        if len(args) > len(param_order):
            raise TypeError(f"{opname}: too many positional arguments")
        return _dispatch.invoke(op, inputs, kwargs, out=out)

    wrapper.__name__ = opname
    wrapper.__qualname__ = opname
    wrapper.__doc__ = op.signature_doc()
    return wrapper


def _new_module(name: str) -> types.ModuleType:
    mod = types.ModuleType(f"{__name__}.{name}")
    sys.modules[mod.__name__] = mod
    return mod


random = _new_module("random")
linalg = _new_module("linalg")
contrib = _new_module("contrib")
op = _new_module("op")
_internal = _new_module("_internal")

_this = sys.modules[__name__]


def _expose():
    for opname in _registry.list_ops():
        operator = _registry.get(opname)
        fn = _make_wrapper(opname, operator)
        if opname.startswith("_contrib_"):
            setattr(contrib, opname[len("_contrib_"):], fn)
        elif opname.startswith("_random_"):
            setattr(random, opname[len("_random_"):], fn)
        elif opname.startswith("_sample_"):
            setattr(random, opname[1:], fn)      # nd.random.sample_uniform
            setattr(_this, opname[1:], fn)       # nd.sample_uniform (parity)
        elif opname.startswith("_linalg_"):
            setattr(linalg, opname[len("_linalg_"):], fn)
        elif opname.startswith("_"):
            setattr(_internal, opname, fn)
        else:
            if opname in ("BilinearResize2D", "AdaptiveAvgPooling2D", "ROIAlign",
                          "MultiBoxPrior", "box_iou", "box_nms"):
                setattr(contrib, opname, fn)
            else:
                if not hasattr(_this, opname):
                    setattr(_this, opname, fn)
                setattr(op, opname, fn)
        # NDArray convenience methods (the reference generates these too)
        if (operator.num_inputs in (1, 2) and opname[0].isalpha()
                and opname[0].islower() and not hasattr(NDArray, opname)):
            setattr(NDArray, opname, _as_method(fn))


def _as_method(fn):
    def method(self, *args, **kwargs):
        return fn(self, *args, **kwargs)
    method.__doc__ = fn.__doc__
    return method


_expose()
_registry.install_binary_helpers(_this)

# `_shuffle` is exposed as nd.random.shuffle in the reference
from . import sparse                      # noqa: E402
from .sparse import (CSRNDArray, RowSparseNDArray, csr_matrix,  # noqa: E402
                     row_sparse_array)


def _nd_tostype(self, stype):
    """ref: NDArray.tostype — convert between storage types."""
    if stype == "default":
        return self
    if stype == "csr":
        return sparse.csr_matrix(self)
    if stype == "row_sparse":
        return sparse.row_sparse_array(self)
    raise ValueError(f"unknown storage type {stype!r}")


NDArray.tostype = _nd_tostype

# control-flow ops take Python callables, so they bypass the registry
# (ref: python/mxnet/ndarray/contrib.py foreach/while_loop/cond)
from ..ops import control_flow as _control_flow  # noqa: E402

contrib.foreach = _control_flow.foreach
contrib.while_loop = _control_flow.while_loop
contrib.cond = _control_flow.cond

random.shuffle = getattr(_internal, "_shuffle")
random.bernoulli = _make_wrapper("_random_bernoulli",
                                 _registry.get("_random_bernoulli"))
random.multinomial = getattr(random, "sample_multinomial", None) or \
    _make_wrapper("_sample_multinomial", _registry.get("_sample_multinomial"))

# dtype-preserving aliases the reference exposes at top level
zeros_like = getattr(_this, "zeros_like")
ones_like = getattr(_this, "ones_like")


def dot(lhs, rhs, transpose_a=False, transpose_b=False, out=None):
    """nd.dot — explicit def so positional flags work (ref: tensor/dot.cc)."""
    return _dispatch.invoke("dot", [lhs, rhs],
                            dict(transpose_a=transpose_a,
                                 transpose_b=transpose_b), out=out)


def split(data, num_outputs, axis=1, squeeze_axis=False):
    return _dispatch.invoke("SliceChannel", [data],
                            dict(num_outputs=num_outputs, axis=axis,
                                 squeeze_axis=squeeze_axis))

"""NDArray — the framework's tensor type.

TPU-native re-design of the reference's async, ref-counted tensor
(ref: include/mxnet/ndarray.h NDArray; src/ndarray/ndarray.cc). Design
mapping (SURVEY §7 translation table):

- asynchronous evaluation: native to JAX/PjRt — ops return before compute
  finishes; ``wait_to_read`` = ``block_until_ready``;
- mutability: the *API* stays mutable (``x += 1``, ``x[:] = v``, ``out=``),
  implemented by rebinding the handle to a new immutable ``jax.Array``
  (in-jit mutation uses buffer donation instead);
- engine var-dependencies: data-flow ordering is tracked by the runtime, so
  there is nothing to declare;
- views (``Slice/At``) are copy-on-read, NOT write-through aliases — a
  documented divergence from the reference (SURVEY §7 "hard parts" #1).
"""
from __future__ import annotations

import os
import struct
import zlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import _dispatch, engine
from ..base import MXNetError, _as_np_dtype, mx_real_t
from ..context import Context, current_context

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "eye", "linspace", "concat", "stack", "save", "load", "waitall",
           "moveaxis", "onehot_encode", "imdecode"]


class NDArray:
    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_tape_node",
                 "_tape_out_idx", "_sparse", "_sparse_used", "_zeroed",
                 "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None,
                 _skip_device_put: bool = False):
        ctx = ctx if ctx is not None else current_context()
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array) or dtype is not None:
            data = jnp.asarray(data, dtype=_as_np_dtype(dtype) if dtype else None)
        if not _skip_device_put:
            data = jax.device_put(data, ctx.jax_device)
        self._data = data
        self._ctx = ctx
        self._grad = None
        self._grad_req = "write"
        self._tape_node = None
        self._tape_out_idx = 0

    # -- core properties ----------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def ctx(self) -> Context:
        return self._ctx

    context = ctx

    @property
    def stype(self):
        return "default"   # sparse storage types are not implemented yet

    @property
    def grad(self):
        # a row-sparse deposit (Embedding sparse_grad backward) lives on
        # the buffer as `_sparse`; surface it so raw-autograd users never
        # read the stale dense buffer
        if self._grad is not None:
            rs = getattr(self._grad, "_sparse", None)
            if rs is not None:
                return rs
        return self._grad

    @property
    def T(self):
        return _invoke1("transpose", self)

    @property
    def handle(self):
        return self._data  # the "C handle" is the jax.Array itself

    def _rebind(self, new_data):
        """Point this handle at new contents — the mutation mechanism."""
        self._data = new_data

    # -- sync / host transfer ----------------------------------------------
    def wait_to_read(self):
        """ref: NDArray::WaitToRead."""
        jax.block_until_ready(self._data)

    def wait_to_write(self):
        jax.block_until_ready(self._data)

    def asnumpy(self) -> np.ndarray:
        arr = np.asarray(jax.device_get(self._data))
        return arr

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def astype(self, dtype, copy=True):
        return _invoke1("Cast", self, dtype=np.dtype(_as_np_dtype(dtype)).name)

    def copy(self):
        return NDArray(self._data, ctx=self._ctx, _skip_device_put=True)

    def copyto(self, other):
        """ref: NDArray::CopyFromTo / mx.nd.NDArray.copyto."""
        if isinstance(other, Context):
            return self.as_in_context(other)
        other._rebind(jax.device_put(self._data, other.ctx.jax_device)
                      .astype(other._data.dtype))
        return other

    def as_in_context(self, ctx: Context):
        if ctx == self._ctx:
            return self
        out = NDArray(jax.device_put(self._data, ctx.jax_device), ctx=ctx,
                      _skip_device_put=True)
        out._tape_node = self._tape_node
        out._tape_out_idx = self._tape_out_idx
        return out

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        if stype != "default":
            raise MXNetError("sparse storage types not supported yet")
        return self

    # -- autograd -----------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        """ref: python/mxnet/ndarray/ndarray.py attach_grad — marks this array
        as a differentiation leaf (detaches it from any recorded graph)."""
        self._grad = zeros(self.shape, dtype=self.dtype, ctx=self._ctx)
        self._grad._zeroed = True     # fresh buffer: sparse add-deposits
        self._grad_req = grad_req     # may stay sparse
        self._tape_node = None
        self._tape_out_idx = 0

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx, _skip_device_put=True)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- shape ops as methods ------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if kwargs.get("shape"):
            shape = tuple(kwargs["shape"])
        return _invoke1("Reshape", self, shape=shape,
                        reverse=kwargs.get("reverse", False))

    def reshape_like(self, other):
        return _invoke1("Reshape", self, shape=other.shape)

    def broadcast_to(self, shape):
        return _invoke1("broadcast_to", self, shape=shape)

    def broadcast_like(self, other):
        return _dispatch.invoke("broadcast_like", [self, other], {})

    def expand_dims(self, axis):
        return _invoke1("expand_dims", self, axis=axis)

    def flatten(self):
        return _invoke1("Flatten", self)

    def squeeze(self, axis=None):
        return _invoke1("squeeze", self, axis=axis)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _invoke1("transpose", self, axes=axes or None)

    def swapaxes(self, dim1, dim2):
        return _invoke1("SwapAxis", self, dim1=dim1, dim2=dim2)

    def flip(self, axis):
        return _invoke1("reverse", self, axis=axis)

    def slice(self, begin, end, step=None):
        return _invoke1("slice", self, begin=begin, end=end, step=step)

    def slice_axis(self, axis, begin, end):
        return _invoke1("slice_axis", self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        return _dispatch.invoke("take", [self, indices], dict(axis=axis, mode=mode))

    def one_hot(self, depth, **kw):
        return _invoke1("one_hot", self, depth=depth, **kw)

    def pad(self, mode="constant", pad_width=None, constant_value=0.0):
        return _invoke1("Pad", self, mode=mode, pad_width=pad_width,
                        constant_value=constant_value)

    def clip(self, a_min=None, a_max=None):
        return _invoke1("clip", self, a_min=a_min, a_max=a_max)

    def tile(self, reps):
        return _invoke1("tile", self, reps=reps)

    def repeat(self, repeats, axis=None):
        return _invoke1("repeat", self, repeats=repeats, axis=axis)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _invoke1("SliceChannel", self, num_outputs=num_outputs,
                        axis=axis, squeeze_axis=squeeze_axis)

    # -- python protocol -----------------------------------------------------
    def __repr__(self):
        return f"\n{self.asnumpy()}\n<NDArray {self.shape} @{self._ctx}>"

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an NDArray with multiple "
                             "elements is ambiguous")
        return bool(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, key):
        # indexing under autograd routes through a recorded op so grads flow
        from ..autograd import is_recording
        idx = _convert_index(key)
        if is_recording() and (self._tape_node is not None or self._grad is not None):
            return _dispatch.invoke(_getitem_op(idx), [self], {})
        return NDArray(self._data[idx], ctx=self._ctx, _skip_device_put=True)

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        idx = _convert_index(key)
        self._rebind(self._data.at[idx].set(jnp.asarray(value, dtype=self._data.dtype)))

    # arithmetic -------------------------------------------------------------
    def __add__(self, other):
        return _binary(self, other, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return _binary(self, other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _invoke1("_rminus_scalar", self, scalar=float(other))

    def __mul__(self, other):
        return _binary(self, other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _binary(self, other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _invoke1("_rdiv_scalar", self, scalar=float(other))

    def __mod__(self, other):
        return _binary(self, other, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, other):
        return _invoke1("_rmod_scalar", self, scalar=float(other))

    def __pow__(self, other):
        return _binary(self, other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        return _invoke1("_rpower_scalar", self, scalar=float(other))

    def __neg__(self):
        return _invoke1("negative", self)

    def __abs__(self):
        return _invoke1("abs", self)

    def __eq__(self, other):
        return _binary(self, other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):
        return _binary(self, other, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return _binary(self, other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return _binary(self, other, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return _binary(self, other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return _binary(self, other, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # in-place: rebind the handle (ref: engine write-var mutation)
    def __iadd__(self, other):
        res = self.__add__(other)
        self._rebind(res._data)
        self._tape_node, self._tape_out_idx = res._tape_node, res._tape_out_idx
        return self

    def __isub__(self, other):
        res = self.__sub__(other)
        self._rebind(res._data)
        self._tape_node, self._tape_out_idx = res._tape_node, res._tape_out_idx
        return self

    def __imul__(self, other):
        res = self.__mul__(other)
        self._rebind(res._data)
        self._tape_node, self._tape_out_idx = res._tape_node, res._tape_out_idx
        return self

    def __itruediv__(self, other):
        res = self.__truediv__(other)
        self._rebind(res._data)
        self._tape_node, self._tape_out_idx = res._tape_node, res._tape_out_idx
        return self

    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx": str(self._ctx)}

    def __setstate__(self, state):
        ctx = Context(state["ctx"].split("(")[0],
                      int(state["ctx"].split("(")[1].rstrip(")")))
        self._data = jnp.asarray(state["data"])
        self._ctx = ctx
        self._grad = None
        self._grad_req = "write"
        self._tape_node = None
        self._tape_out_idx = 0


def _invoke1(op, x, **kwargs):
    return _dispatch.invoke(op, [x], kwargs)


def _binary(lhs, rhs, broadcast_op, scalar_op):
    if isinstance(rhs, NDArray):
        return _dispatch.invoke(broadcast_op, [lhs, rhs], {})
    return _dispatch.invoke(scalar_op, [lhs], {"scalar": float(rhs)})


def _convert_index(key):
    if isinstance(key, NDArray):
        return key._data
    if isinstance(key, tuple):
        return tuple(k._data if isinstance(k, NDArray) else k for k in key)
    return key


def _getitem_op(idx):
    """A one-off differentiable gather op for recorded indexing."""
    from ..ops.registry import Operator
    return Operator(name="_getitem", fn=lambda x: x[idx], num_inputs=1)


# ---------------------------------------------------------------------------
# creation functions (ref: python/mxnet/ndarray/ndarray.py + utils)
# ---------------------------------------------------------------------------
def array(source_array, ctx=None, dtype=None) -> NDArray:
    """ref: mx.nd.array — dtype defaults to the source's dtype for ndarray
    inputs, float32 otherwise (list/scalar inputs)."""
    if isinstance(source_array, NDArray):
        src = source_array.asnumpy()
    elif isinstance(source_array, (np.ndarray, jax.Array)):
        src = np.asarray(source_array)
    else:
        src = np.asarray(source_array)
        if dtype is None:
            dtype = mx_real_t
    if dtype is None and src.dtype == np.float64:
        dtype = mx_real_t   # reference defaults to float32
    return NDArray(src, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.zeros(shape, dtype=_as_np_dtype(dtype)), ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.ones(shape, dtype=_as_np_dtype(dtype)), ctx=ctx)


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jnp.full(shape, val, dtype=_as_np_dtype(dtype)), ctx=ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    out = jnp.arange(start, stop, step, dtype=_as_np_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return NDArray(out, ctx=ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    return NDArray(jnp.eye(N, M or N, k=k, dtype=_as_np_dtype(dtype)), ctx=ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None) -> NDArray:
    return NDArray(jnp.linspace(start, stop, num, endpoint=endpoint,
                                dtype=_as_np_dtype(dtype)), ctx=ctx)


def moveaxis(tensor, source, destination) -> NDArray:
    return _dispatch.invoke("moveaxis", [tensor],
                            {"source": source, "destination": destination})


def concat(*args, dim=1):
    return _dispatch.invoke("Concat", list(args), {"dim": dim})


def stack(*args, axis=0):
    return _dispatch.invoke("stack", list(args), {"axis": axis})


def onehot_encode(indices, out):
    depth = out.shape[1]
    res = _invoke1("one_hot", indices, depth=depth)
    out._rebind(res._data)
    return out


def imdecode(buf, **kwargs):
    raise MXNetError("nd.imdecode requires the image module; use "
                     "mxnet_tpu.image.imdecode")


def waitall():
    engine.waitall()


# ---------------------------------------------------------------------------
# save / load — the `.params` container (ref: src/ndarray/ndarray.cc
# NDArray::Save/Load via MXNDArraySave). Binary layout follows the reference's
# documented structure (list magic + per-array magic, shape, context, dtype).
#
# Crash consistency (docs/checkpointing.md): the writer goes through
# resilience.atomic (tmp + fsync + os.replace — a reader can never see a
# torn file) and stamps the format-flag word in the header with
# _FMT_CRC: each array entry is followed by its CRC32 and the file ends
# with a <body-length, footer-magic> footer, so load() proves integrity
# up front. Reference-era files (flag word 0) still load, minus the
# checksum proof. Every read is bounds-checked: truncation or corruption
# raises a structured MXNetError, never struct.error or silent garbage.
# ---------------------------------------------------------------------------
_LIST_MAGIC = 0x112          # kMXAPINDArrayListMagic
_ND_MAGIC = 0xF993FAC9       # NDArray binary magic (v2)
_FOOTER_MAGIC = 0x4D585450_43524333   # "MXTP CRC3"
_FMT_LEGACY, _FMT_CRC = 0, 1
# footer: <Q body_len> <I names_crc> <I reserved> <Q footer_magic>
_FOOTER_BYTES = 24

_DTYPE_CODE = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
               "int32": 4, "int8": 5, "int64": 6, "bool": 7, "bfloat16": 12}
_CODE_DTYPE = {v: k for k, v in _DTYPE_CODE.items()}


def save(fname: str, data):
    """Save NDArrays (list or str->NDArray dict) to a .params file.

    Atomic: the bytes land in a same-directory temp file that is
    fsynced and renamed over ``fname`` — a crash at any point leaves
    either the previous file or the new one, never a torn mix."""
    from ..resilience.atomic import atomic_write
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    with atomic_write(fname, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, _FMT_CRC))
        f.write(struct.pack("<Q", len(arrays)))
        for arr in arrays:
            crc = _write_ndarray(f, arr)
            f.write(struct.pack("<I", crc))
        tail = [struct.pack("<Q", len(names))]
        for n in names:
            b = n.encode("utf-8")
            tail.append(struct.pack("<Q", len(b)))
            tail.append(b)
        tail_bytes = b"".join(tail)
        f.write(tail_bytes)
        # f.nbytes: the atomic handle's running byte count = body length
        f.write(struct.pack("<QIIQ", f.nbytes,
                            zlib.crc32(tail_bytes) & 0xFFFFFFFF, 0,
                            _FOOTER_MAGIC))


def _write_ndarray(f, arr: NDArray) -> int:
    """Serialize one array; returns the CRC32 of the entry's bytes."""
    np_arr = arr.asnumpy()
    pieces = [struct.pack("<I", _ND_MAGIC),
              struct.pack("<I", len(np_arr.shape))]
    for s in np_arr.shape:
        pieces.append(struct.pack("<q", s))
    pieces.append(struct.pack("<ii", arr.ctx.device_typeid,
                              arr.ctx.device_id))
    dt = np.dtype(np_arr.dtype).name
    if dt not in _DTYPE_CODE:
        # stamping an unknown dtype as float32 would let the CRCs
        # certify bytes that load() then misdecodes — the silent-garbage
        # class the strict load path exists to kill; refuse symmetrically
        raise MXNetError(f"nd.save: dtype {dt!r} has no .params dtype "
                         f"code (supported: {sorted(_DTYPE_CODE)})")
    pieces.append(struct.pack("<i", _DTYPE_CODE[dt]))
    if dt == "bfloat16":
        np_arr = np_arr.view(np.uint16)
    pieces.append(np_arr.tobytes())
    crc = 0
    for piece in pieces:
        f.write(piece)
        crc = zlib.crc32(piece, crc)
    return crc & 0xFFFFFFFF


class _BoundedReader:
    """Bounds-checked reads over the container body: a short or
    out-of-bounds read is a structured truncation error (the torn-file
    class this format exists to catch), never struct.error. Optionally
    accumulates a CRC over everything read (per-entry verification)."""

    def __init__(self, f, fname, limit):
        self._f = f
        self._fname = fname
        self._limit = limit
        self._crc = None

    def read(self, n, what):
        if n < 0 or self._f.tell() + n > self._limit:
            raise MXNetError(
                f"{self._fname}: truncated or corrupt .params file — "
                f"{what} wants {n} bytes but only "
                f"{max(self._limit - self._f.tell(), 0)} remain (was the "
                "save interrupted?)")
        data = self._f.read(n)
        if len(data) != n:
            raise MXNetError(
                f"{self._fname}: truncated .params file — short read "
                f"({len(data)}/{n} bytes) for {what}")
        if self._crc is not None:
            self._crc = zlib.crc32(data, self._crc)
        return data

    def unpack(self, fmt, what):
        return struct.unpack(fmt, self.read(struct.calcsize(fmt), what))

    def begin_crc(self):
        self._crc = 0

    def end_crc(self) -> int:
        crc, self._crc = self._crc, None
        return crc & 0xFFFFFFFF

    def tell(self):
        return self._f.tell()


def load(fname: str):
    """Load a .params file -> list or dict of NDArrays.

    Integrity is verified up front for files written by this package
    (length footer + per-entry CRC32); any truncation or corruption
    raises MXNetError naming the defect."""
    with open(fname, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size < 24:
            raise MXNetError(f"{fname}: truncated .params file — "
                             f"{size} bytes is smaller than any header")
        magic, fmt = struct.unpack("<QQ", f.read(16))
        if magic != _LIST_MAGIC:
            raise MXNetError(f"{fname}: bad magic {magic:#x} — not an "
                             "NDArray save file")
        names_crc = None
        if fmt == _FMT_CRC:
            if size < 16 + _FOOTER_BYTES:
                raise MXNetError(f"{fname}: truncated .params file — "
                                 "no room for the integrity footer")
            limit = size - _FOOTER_BYTES
            f.seek(limit)
            body_len, names_crc, _resv, fmagic = struct.unpack(
                "<QIIQ", f.read(_FOOTER_BYTES))
            if fmagic != _FOOTER_MAGIC or body_len != limit:
                raise MXNetError(
                    f"{fname}: truncated or corrupt .params file — "
                    "footer missing or inconsistent (the save was "
                    "interrupted before commit)")
            f.seek(16)
        elif fmt == _FMT_LEGACY:
            limit = size
        else:
            raise MXNetError(f"{fname}: unsupported .params format flag "
                             f"{fmt} — written by a newer version?")
        verify = fmt == _FMT_CRC
        r = _BoundedReader(f, fname, limit)
        (count,) = r.unpack("<Q", "array count")
        if count > limit:                    # cheap sanity vs corrupt counts
            raise MXNetError(f"{fname}: corrupt .params file — implausible "
                             f"array count {count}")
        arrays = []
        for i in range(count):
            arr = _read_ndarray(r, verify, fname, i)
            arrays.append(arr)
        if verify:
            r.begin_crc()
        (n_names,) = r.unpack("<Q", "name count")
        if n_names > limit:
            raise MXNetError(f"{fname}: corrupt .params file — implausible "
                             f"name count {n_names}")
        names = []
        for i in range(n_names):
            (ln,) = r.unpack("<Q", f"name {i} length")
            try:
                names.append(r.read(ln, f"name {i}").decode("utf-8"))
            except UnicodeDecodeError as e:
                raise MXNetError(f"{fname}: corrupt .params file — "
                                 f"name {i} is not valid UTF-8") from e
        if verify:
            if r.end_crc() != names_crc:
                raise MXNetError(f"{fname}: checksum mismatch in the name "
                                 "table — the file is corrupt")
            if r.tell() != limit:
                raise MXNetError(
                    f"{fname}: corrupt .params file — "
                    f"{limit - r.tell()} unexpected trailing bytes")
    if names:
        return dict(zip(names, arrays))
    return arrays


def _read_ndarray(r: _BoundedReader, verify: bool, fname: str,
                  index: int) -> NDArray:
    what = f"array entry {index}"
    r.begin_crc()
    (magic,) = r.unpack("<I", what)
    if magic != _ND_MAGIC:
        raise MXNetError(f"{fname}: corrupt NDArray entry {index} "
                         f"(bad entry magic {magic:#x})")
    (ndim,) = r.unpack("<I", what)
    if ndim > 64:
        raise MXNetError(f"{fname}: corrupt NDArray entry {index} — "
                         f"implausible rank {ndim}")
    shape = tuple(r.unpack("<q", what)[0] for _ in range(ndim))
    if any(s < 0 for s in shape):
        raise MXNetError(f"{fname}: corrupt NDArray entry {index} — "
                         f"negative dimension in shape {shape}")
    _dev_type, _dev_id = r.unpack("<ii", what)
    (dtype_code,) = r.unpack("<i", what)
    dt = _CODE_DTYPE.get(dtype_code)
    if dt is None:
        raise MXNetError(
            f"{fname}: unknown dtype code {dtype_code} in entry {index} "
            "— file from a newer format or corrupt (refusing to guess "
            "a dtype)")
    count = int(np.prod(shape)) if ndim else 1
    if dt == "bfloat16":
        import ml_dtypes
        raw = np.frombuffer(r.read(count * 2, what + " data"),
                            dtype=np.uint16)
        np_arr = raw.view(ml_dtypes.bfloat16).reshape(shape)
    else:
        npdt = np.dtype(dt)
        np_arr = np.frombuffer(r.read(count * npdt.itemsize, what + " data"),
                               dtype=npdt).reshape(shape)
    crc = r.end_crc()
    if verify:
        (want,) = r.unpack("<I", what + " checksum")
        if crc != want:
            raise MXNetError(
                f"{fname}: checksum mismatch in entry {index} "
                f"(stored {want:#010x}, computed {crc:#010x}) — the "
                "file is corrupt")
    return NDArray(np_arr)

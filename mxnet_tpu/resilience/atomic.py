"""Crash-consistent file writes: tmp + flush + fsync + ``os.replace``.

A preemption mid-``nd.save`` used to leave a torn ``.params`` file that
``load`` would misparse. This module is the one sanctioned write path
for durable artifacts (graftlint rule G7 flags direct ``open(path,
"wb")`` writes): the caller streams into a same-directory temp file,
which is fsynced and atomically renamed over the target, so a reader
can only ever observe the complete old bytes or the complete new bytes.

Fault-injection seam: :mod:`mxnet_tpu.testing.faults` installs a hook
via :func:`set_fault_hook`; the hook is consulted at every named phase
(``open``, ``write`` with a cumulative byte count, ``fsync``,
``replace``, ``after_replace``, ``dir_fsync`` — plus points other
modules register through :func:`trip`, e.g. the commit protocol's
``publish``/``gc``). The crash-matrix tests kill the writer at each
phase and prove the old-or-new guarantee.

Cleanup policy mirrors real crashes: an ordinary ``Exception`` unlinks
the temp file (no litter from recoverable errors); a ``BaseException``
— the harness's ``SimulatedCrash``, KeyboardInterrupt, a real kill —
leaves the torn temp on disk, exactly like a dead process would, and
:func:`sweep_tmp` (run by checkpoint GC) collects it later.

Stdlib-only; transient fsync/replace failures ride
``resilience.retry`` (journaled, bounded).
"""
from __future__ import annotations

import contextlib
import itertools
import os

from ..diagnostics.journal import get_journal
from .retry import is_disk_full, note_disk_full, retry_call

__all__ = ["atomic_write", "fsync_dir", "set_fault_hook", "sweep_tmp",
           "trip"]

_TMP_MARK = ".tmp."
# per-call staging suffix: <path>.tmp.<pid>.<n>.  The counter makes
# concurrent writers to the SAME path stage into DIFFERENT temp files —
# pid alone is not unique across threads, and the pre-fix heartbeat
# beat() had to hold a lock across this whole write only to keep the
# daemon and a lifecycle publish from tearing each other's staging file
# (graftlint G15's lock-held-file-I/O class). Replace order decides the
# winner; both candidates are whole documents, so readers still only
# ever observe complete old or complete new bytes.
_tmp_seq = itertools.count()

_fault_hook = None


def set_fault_hook(hook):
    """Install (or, with None, remove) the process-wide fault hook;
    returns the previous hook so tests can nest/restore."""
    global _fault_hook
    prev = _fault_hook
    _fault_hook = hook
    return prev


def trip(point: str, path: str, nbytes: int | None = None,
         size: int | None = None) -> None:
    """Consult the fault hook at a named phase (``nbytes`` = bytes
    already written, ``size`` = bytes about to be written, for the
    ``write`` point). Library code calls this at its own commit points
    (e.g. ``commit.publish``) so one hook drives the whole crash
    matrix; a no-op unless a hook is installed."""
    if _fault_hook is not None:
        _fault_hook(point, path=path, nbytes=nbytes, size=size)


class _Handle:
    """File wrapper that counts written bytes and exposes the ``write``
    fault point (crash-after-N-bytes injection)."""

    def __init__(self, f, path):
        self._f = f
        self._path = path
        self.nbytes = 0

    def write(self, data):
        trip("write", self._path, nbytes=self.nbytes, size=len(data))
        n = self._f.write(data)
        self.nbytes += len(data)
        return n

    def __getattr__(self, name):
        return getattr(self._f, name)


def fsync_dir(path: str) -> None:
    """Durably record a rename: fsync the parent directory. Failures are
    journaled, not raised — on filesystems that reject directory fsync
    (some tmpfs/NFS builds) the rename itself already happened and the
    save must not be reported as lost."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return

    def _do_fsync():
        trip("dir_fsync", d)
        os.fsync(fd)

    try:
        retry_call(_do_fsync, what=f"fsync_dir:{d}")
    except OSError as exc:
        get_journal().event("fsync_dir_failed", dir=d,
                            error=type(exc).__name__,
                            detail=str(exc)[:200])
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path, mode: str = "wb", encoding: str | None = None,
                 durable: bool = True):
    """Write ``path`` all-or-nothing: yield a file handle over
    ``<path>.tmp.<pid>.<n>`` (per-call unique — concurrent writers to
    one path never share a staging file); on clean exit flush + fsync +
    ``os.replace`` into place (+ parent-directory fsync when
    ``durable``).

    ``mode`` must be a write mode ('wb', 'w'); text mode takes
    ``encoding``. The temp lives in the target's directory so the
    rename never crosses a filesystem boundary."""
    path = os.fspath(path)
    tmp = f"{path}{_TMP_MARK}{os.getpid()}.{next(_tmp_seq)}"
    kwargs = {} if "b" in mode else {"encoding": encoding or "utf-8"}
    try:
        trip("open", tmp)
        f = open(tmp, mode, **kwargs)
    except Exception as exc:
        # nothing staged yet — but an exhausted disk discovered at open
        # still deserves its (deduped) degrade record
        if is_disk_full(exc):
            note_disk_full(path, op="atomic_write")
        raise

    def _do_fsync():
        trip("fsync", tmp)
        os.fsync(f.fileno())

    def _do_replace():
        trip("replace", path)
        os.replace(tmp, path)

    try:
        try:
            yield _Handle(f, tmp)
            f.flush()
            if durable:
                retry_call(_do_fsync, what=f"fsync:{tmp}")
            else:
                trip("fsync", tmp)
        finally:
            f.close()
        retry_call(_do_replace, what=f"replace:{path}")
        trip("after_replace", path)
        if durable:
            fsync_dir(path)
    except Exception as exc:
        # recoverable failure: don't litter. A BaseException (simulated
        # or real crash) skips this, leaving the torn tmp like a dead
        # process would. On a full disk the unlink comes FIRST — it is
        # the one action that frees space — then the deduped degrade
        # record (retry_call already noted fsync/replace exhaustion;
        # the dedup set keeps this to one record per target path).
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        if is_disk_full(exc):
            note_disk_full(path, op="atomic_write")
        raise


def sweep_tmp(dirpath: str, prefix: str | None = None) -> list[str]:
    """Remove stale ``*.tmp.<pid>`` litter left by crashed writers in
    ``dirpath`` (optionally only names starting with ``prefix``).
    Returns the removed names; missing dir is a no-op."""
    removed = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return removed
    for name in names:
        if _TMP_MARK not in name:
            continue
        if prefix is not None and not name.startswith(prefix):
            continue
        with contextlib.suppress(OSError):
            os.unlink(os.path.join(dirpath, name))
            removed.append(name)
    return removed

"""Directory-granular commit protocol for multi-file checkpoints.

The multi-host shard writer used to expose a rank-0 meta file whose
shard set never finished — a reader could pick up a checkpoint that was
never completely written. This module gives every multi-file save one
commit point (the CheckFreq/Gemini discipline: cheap frequent
checkpoints are only worth taking if recovery can trust them):

Layout under a checkpoint root::

    <root>/step-00000042.tmp/   staging — readers always ignore it
    <root>/step-00000042/       committed — contains MANIFEST.json
    <root>/latest               pointer file (a hint; re-validated)

Writer protocol (single writer per root; multi-host ranks share the
root on a common filesystem and the caller supplies the barrier):

1. rank 0 ``prepare_stage`` (wipes a half-written stage from a crashed
   attempt at the same step); barrier.
2. every rank writes its files into the stage dir via ``atomic_write``;
   barrier.
3. rank 0 ``finalize``: writes ``MANIFEST.json`` (file list + CRC32 +
   sizes + step + caller meta) atomically INSIDE the stage, renames the
   stage to ``step-N/`` (the commit point — a visible step dir always
   holds a complete manifest), rewrites ``latest``, then GC:
   keep-last-k committed steps plus stale ``*.tmp`` stages.

Reader protocol: try the ``latest`` hint, then every committed step
newest-first; a dir whose manifest is missing/corrupt or whose files
fail CRC is skipped (caller-journaled) and the next-newest tried — so
restore always lands on the newest checkpoint that is provably intact.

Stdlib-only (no jax, no ndarray): the diagnostics doctor validates
manifests from contexts where the runtime itself may be broken.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import zlib

from . import atomic

__all__ = ["MANIFEST", "committed_steps", "doctor_report", "file_crc",
           "finalize", "find_restorable", "gc_steps", "prepare_stage",
           "read_latest", "read_manifest", "stage_dir", "step_dir",
           "validate_step", "write_latest", "write_manifest"]

MANIFEST = "MANIFEST.json"
LATEST = "latest"
FORMAT = 1

_STEP_RE = re.compile(r"^step-(\d{8})$")


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step-{int(step):08d}")


def stage_dir(root: str, step: int) -> str:
    return step_dir(root, step) + ".tmp"


def prepare_stage(root: str, step: int) -> str:
    """Create a fresh staging dir for ``step``; a half-written stage
    from a previous crashed attempt at the same step is wiped."""
    s = stage_dir(root, step)
    if os.path.isdir(s):
        shutil.rmtree(s)
    os.makedirs(s, exist_ok=True)
    return s


def file_crc(path: str, chunksize: int = 1 << 20):
    """(crc32, size) of a file's bytes, streamed."""
    crc, size = 0, 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunksize)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return crc & 0xFFFFFFFF, size


def _payload_files(dirpath: str) -> list[str]:
    """Regular files in a step dir that belong to the checkpoint: the
    manifest itself and crashed-writer tmp litter don't count."""
    out = []
    for name in sorted(os.listdir(dirpath)):
        if name == MANIFEST or atomic._TMP_MARK in name:
            continue
        if os.path.isfile(os.path.join(dirpath, name)):
            out.append(name)
    return out


def write_manifest(dirpath: str, step: int, meta: dict | None = None):
    """Checksum every payload file in ``dirpath`` and write the manifest
    atomically. Returns the manifest document."""
    files = {}
    for name in _payload_files(dirpath):
        crc, size = file_crc(os.path.join(dirpath, name))
        files[name] = {"crc32": crc, "size": size}
    if not files:
        raise ValueError(f"{dirpath}: nothing staged — refusing to "
                         "commit an empty checkpoint")
    doc = {"format": FORMAT, "step": int(step), "files": files,
           "meta": meta or {}}
    with atomic.atomic_write(os.path.join(dirpath, MANIFEST), "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    return doc


def read_manifest(dirpath: str) -> dict:
    """Parse + schema-check a step dir's manifest. Raises ValueError
    (with a reason) on anything short of a well-formed document."""
    path = os.path.join(dirpath, MANIFEST)
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise ValueError(f"no manifest ({e.strerror or e})") from e
    except ValueError as e:
        raise ValueError(f"manifest not valid JSON ({e})") from e
    if not isinstance(doc, dict) or doc.get("format") != FORMAT \
            or not isinstance(doc.get("files"), dict) \
            or not isinstance(doc.get("step"), int):
        raise ValueError("manifest malformed or unsupported format")
    return doc


def validate_step(root: str, step: int) -> dict:
    """Prove a committed step intact: manifest well-formed, every listed
    file present with matching size + CRC32, no listed file missing.
    Returns the manifest; raises ValueError naming the defect."""
    d = step_dir(root, step)
    doc = read_manifest(d)
    if doc["step"] != int(step):
        raise ValueError(f"manifest step {doc['step']} != dir step {step}")
    for name, want in doc["files"].items():
        path = os.path.join(d, name)
        if not os.path.isfile(path):
            raise ValueError(f"missing file {name!r}")
        crc, size = file_crc(path)
        if size != want.get("size"):
            raise ValueError(f"{name!r}: size {size} != manifest "
                             f"{want.get('size')}")
        if crc != want.get("crc32"):
            raise ValueError(f"{name!r}: CRC mismatch (torn or corrupt)")
    return doc


def committed_steps(root: str) -> list[int]:
    """Step numbers of committed dirs (name-matched; ``*.tmp`` staging
    is invisible by construction), ascending."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    steps = []
    for name in names:
        m = _STEP_RE.match(name)
        if m and os.path.isdir(os.path.join(root, name)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def write_latest(root: str, step: int) -> None:
    with atomic.atomic_write(os.path.join(root, LATEST), "w") as f:
        f.write(f"step-{int(step):08d}\n")


def read_latest(root: str) -> int | None:
    """The ``latest`` pointer's step, or None when absent/garbled (the
    pointer is a hint — a torn pointer must never block restore)."""
    try:
        with open(os.path.join(root, LATEST), encoding="utf-8") as f:
            m = _STEP_RE.match(f.read().strip())
            return int(m.group(1)) if m else None
    except OSError:
        return None


def gc_steps(root: str, keep_last: int | None) -> list[int]:
    """Retention: drop committed steps beyond the newest ``keep_last``
    and sweep stale staging dirs + tmp litter. Returns removed steps.
    ``keep_last`` < 2 keeps no fallback behind the newest checkpoint —
    fine for space-tight runs, but corrupt-latest recovery needs 2+."""
    atomic.trip("gc", root)
    removed = []
    steps = committed_steps(root)
    if keep_last is not None and keep_last >= 1:
        for step in steps[:-keep_last]:
            atomic.trip("gc", step_dir(root, step))
            shutil.rmtree(step_dir(root, step), ignore_errors=True)
            removed.append(step)
    newest = steps[-1] if steps else -1
    try:
        names = os.listdir(root)
    except OSError:
        return removed
    for name in names:
        # staging older than the newest commit can only be a crashed
        # attempt; the CURRENT step's stage is gone by publish-time
        if name.endswith(".tmp") and _STEP_RE.match(name[:-4]):
            if int(_STEP_RE.match(name[:-4]).group(1)) <= newest:
                atomic.trip("gc", os.path.join(root, name))
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
        elif name.startswith(".trash-"):
            # a recommit's moved-aside predecessor; by GC time a newer
            # commit exists, so the safety copy is redundant
            atomic.trip("gc", os.path.join(root, name))
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)
    atomic.sweep_tmp(root)
    return removed


def finalize(root: str, step: int, meta: dict | None = None,
             keep_last: int | None = None) -> dict:
    """Rank-0 commit: manifest → publish rename → latest pointer → GC.
    The rename is the single commit point; every phase before it leaves
    the previous checkpoint untouched."""
    from ..observability import trace as _trace
    with _trace.span("ckpt_commit", root=root, step=int(step)):
        return _finalize(root, step, meta, keep_last)


def _finalize(root, step, meta, keep_last) -> dict:
    stage = stage_dir(root, step)
    doc = write_manifest(stage, step, meta)
    dst = step_dir(root, step)
    trash = None
    if os.path.isdir(dst):
        # recommit of the same step: never destroy the only committed
        # copy before the new one lands — move it aside (invisible to
        # readers but intact on disk across a crash; swept by the next
        # GC once a newer commit exists)
        trash = os.path.join(root, f".trash-{os.path.basename(dst)}"
                                   f"-{os.getpid()}")
        if os.path.isdir(trash):
            shutil.rmtree(trash)
        os.rename(dst, trash)
    atomic.trip("publish", dst)
    os.rename(stage, dst)
    atomic.fsync_dir(dst)
    if trash is not None:
        shutil.rmtree(trash, ignore_errors=True)
    write_latest(root, step)
    gc_steps(root, keep_last)
    return doc


def find_restorable(root: str, on_skip=None):
    """The newest committed step that validates, as ``(step, manifest)``
    — or None. Walks committed steps newest-first; each invalid
    candidate is reported through ``on_skip(step, reason)`` so the
    fallback is never silent.

    Deliberately NOT driven by the ``latest`` pointer: the pointer is
    written after the publish rename, so a crash between the two leaves
    it one step stale — ordering by it would resurrect the older
    checkpoint over a fully-committed newer one. The pointer stays an
    operator-facing hint (doctor reports it)."""
    from ..observability import trace as _trace
    with _trace.span("ckpt_restore_scan", root=root) as sp:
        for step in sorted(committed_steps(root), reverse=True):
            try:
                doc = validate_step(root, step)
                sp.set_attrs(restored_step=step)
                return step, doc
            except ValueError as e:
                if on_skip is not None:
                    on_skip(step, str(e))
        sp.set_attrs(restored_step=None)
    return None


def doctor_report(root: str) -> dict:
    """One-shot health summary of a checkpoint root for the diagnostics
    doctor CLI: pointer, committed steps, latest-step validity, and the
    newest step that would actually restore."""
    steps = committed_steps(root)
    report = {"root": root, "exists": os.path.isdir(root),
              "committed_steps": len(steps),
              "latest_pointer": read_latest(root)}
    newest = steps[-1] if steps else None
    report["newest_step"] = newest
    if newest is not None:
        try:
            validate_step(root, newest)
            report["newest_valid"] = True
        except ValueError as e:
            report["newest_valid"] = False
            report["newest_error"] = str(e)
    skipped = []
    found = find_restorable(root, on_skip=lambda s, r: skipped.append(s))
    report["restorable_step"] = found[0] if found else None
    if skipped:
        report["skipped_steps"] = skipped
    return report

"""``mx.resilience`` — crash-consistency + transient-fault toolkit.

Four parts, all stdlib-only at import (no jax — the same wedge-proof
contract as ``mx.diagnostics``):

- :mod:`.atomic` — ``atomic_write``: tmp + fsync + ``os.replace``, the
  one sanctioned path for durable artifacts (graftlint G7 enforces it),
  with the fault-injection seam the crash-matrix tests drive.
- :mod:`.commit` — the directory commit protocol for multi-file /
  multi-host checkpoints: staged shards, a CRC'd MANIFEST behind a
  single rename commit point, a ``latest`` pointer, keep-last-k GC,
  and validated newest-first restore.
- :mod:`.retry` — bounded exponential backoff + jitter for transient
  filesystem / coordination-service faults, journaled per attempt.
- :mod:`.preempt` — SIGTERM → checkpoint-at-next-step-boundary.

See docs/checkpointing.md for the format, protocol, and the
fault-injection cookbook.
"""
from __future__ import annotations

from . import atomic, commit, preempt, retry
from .atomic import atomic_write, fsync_dir, sweep_tmp
from .commit import find_restorable, validate_step
from .retry import backoff_delays, retry_call

__all__ = ["atomic", "atomic_write", "backoff_delays", "commit",
           "find_restorable", "fsync_dir", "preempt", "retry",
           "retry_call", "sweep_tmp", "validate_step"]

"""SIGTERM-aware preemption handling: checkpoint at the next step
boundary instead of dying mid-write.

Preemptible TPU fleets deliver SIGTERM with a grace window. The default
disposition (or the diagnostics journal's breadcrumb handler) turns
that into process death; this module turns it into a *request*: the
watch latches the signal, the training loop polls it at step
boundaries, saves one checkpoint through the atomic/commit paths, and
exits cleanly. ``BaseModule.fit(checkpoint_prefix=...)`` wires this in
automatically; :func:`checkpoint_on_preempt` is the standalone hook for
hand-rolled loops.

The watch installs itself as the OUTERMOST SIGTERM handler (re-invoke
:func:`install` to re-assert that after other subsystems register
theirs) and deliberately does not chain: graceful save supersedes
immediate death. The journal's ``atexit`` finalizer still writes its
exit breadcrumb on the way out.
"""
from __future__ import annotations

import signal
import threading

from ..diagnostics.journal import get_journal

__all__ = ["PreemptionWatch", "checkpoint_on_preempt", "install",
           "requested"]


class PreemptionWatch:
    """Latches SIGTERM; ``consume()`` hands exactly one caller the duty
    of saving (so a fit loop and a user callback can both poll)."""

    def __init__(self):
        self._flag = threading.Event()
        self._lock = threading.Lock()
        self._consumed = False
        self._installed = False
        self._prev = None
        # ONE bound-method instance: `self._on_term` evaluates to a
        # fresh object per access, so identity checks against what
        # signal.signal stored would never match without this pin
        self._handler = self._on_term

    def _on_term(self, signum, frame):
        self._flag.set()
        get_journal().event("preempt_requested", signum=signum)

    def install(self) -> "PreemptionWatch":
        """(Re-)bind SIGTERM to the watch, remembering the displaced
        disposition for :meth:`uninstall`. Safe to call repeatedly;
        only binds in the main thread (signal module constraint)."""
        try:
            prev = signal.getsignal(signal.SIGTERM)
            if prev is not self._handler:
                self._prev = prev
                signal.signal(signal.SIGTERM, self._handler)
            self._installed = True
        except ValueError:
            pass             # non-main thread: poll-only watch
        return self

    def uninstall(self) -> None:
        """Restore the displaced SIGTERM disposition. Called when the
        polling loop ends (fit returns): a latched-but-never-polled
        watch would make the process silently ignore SIGTERM — worse
        than the default death it replaced."""
        try:
            if self._installed and \
                    signal.getsignal(signal.SIGTERM) is self._handler:
                signal.signal(signal.SIGTERM, self._prev or signal.SIG_DFL)
            self._installed = False
        except ValueError:
            pass

    def requested(self) -> bool:
        return self._flag.is_set()

    def consume(self) -> bool:
        """True exactly once after a SIGTERM: the caller that wins
        saves the checkpoint; everyone else stands down."""
        if not self._flag.is_set():
            return False
        with self._lock:
            if self._consumed:
                return False
            self._consumed = True
            return True

    def clear(self) -> None:
        """Full reset (tests / drivers that survived a drill)."""
        self._flag.clear()
        with self._lock:
            self._consumed = False

    def rearm(self) -> None:
        """Reset only a CONSUMED watch (a new training run starting in
        the same process). A live, unconsumed SIGTERM — a preemption
        that raced startup — stays latched and still triggers the
        boundary save."""
        with self._lock:
            if self._consumed:
                self._consumed = False
                self._flag.clear()


_watch: PreemptionWatch | None = None
_watch_lock = threading.Lock()


def install() -> PreemptionWatch:
    """The process-wide watch, SIGTERM bound (idempotent; re-asserts
    the binding if something else grabbed the signal since)."""
    global _watch
    with _watch_lock:
        if _watch is None:
            _watch = PreemptionWatch()
    return _watch.install()


def requested() -> bool:
    return _watch is not None and _watch.requested()


def checkpoint_on_preempt(module, prefix: str, keep_last: int | None = None):
    """Batch-end callback for hand-rolled loops: after a SIGTERM, save
    ``module``'s checkpoint at the current step boundary (journaled as
    ``preempt_checkpoint``) — once per installation (creating the
    callback re-arms a watch an earlier training run consumed; a live
    unconsumed signal stays latched)."""
    watch = install()
    watch.rearm()

    def _callback(param):
        if not watch.consume():
            return
        module.save_checkpoint(prefix, param.epoch)
        if keep_last:
            from .. import model
            model.gc_checkpoints(prefix, keep_last)
        get_journal().event("preempt_checkpoint", prefix=prefix,
                            epoch=param.epoch, nbatch=param.nbatch)
    return _callback

"""Bounded retry with exponential backoff + jitter for transient faults.

Preemptible fleets see two transient failure families this module
absorbs: coordination-service connects that race the coordinator's own
restart (``kvstore._ensure_distributed``), and checkpoint filesystem
ops over network mounts that return spurious EIO/ESTALE under load
(``resilience.atomic``'s fsync/replace). Both recover on a short
retry far more often than they merit killing a training run.

Contract:

- The delay before retry ``i`` (0-based) is in ``[b_i, b_i*(1+jitter)]``
  where ``b_i = min(base_s * 2**i, max_s)`` — bounds are asserted by
  tests/test_resilience.py, so drivers can budget worst-case stalls.
- Every failed attempt is journaled (``kind: "retry"``) so a flaky
  filesystem is visible in the crash journal, not silent.
- Only exceptions in ``retry_on`` are retried; everything else —
  including BaseException crash stand-ins from the fault-injection
  harness — propagates immediately.
- Resource exhaustion is NOT transient: ENOSPC/EDQUOT fail fast on the
  first attempt (freeing space is the remedy, retrying only burns the
  budget and delays the cleanup that frees the staged temp), with one
  deduped ``disk_full`` journal record per path.

Stdlib-only (no jax): importable from the same wedge-proof contexts as
``diagnostics.journal``.
"""
from __future__ import annotations

import errno
import os
import random
import threading
import time

from ..diagnostics.journal import get_journal

__all__ = ["backoff_delays", "is_disk_full", "note_disk_full",
           "reset_disk_full_notes", "retry_call"]

# exhaustion errnos no retry budget can fix
_FAIL_FAST_ERRNOS = frozenset(
    e for e in (errno.ENOSPC, getattr(errno, "EDQUOT", None))
    if e is not None)

# paths whose disk_full record already landed (dedup: a full disk makes
# EVERY writer fail — one structured record per path tells the story,
# a thousand would bury it and feed the very disk that is full)
_noted_lock = threading.Lock()
_noted_paths: set = set()


def is_disk_full(exc) -> bool:
    """True for the exhaustion errnos (ENOSPC/EDQUOT) that must fail
    fast instead of riding the transient-retry path."""
    return isinstance(exc, OSError) and exc.errno in _FAIL_FAST_ERRNOS


def note_disk_full(path, op: str) -> bool:
    """Journal one structured ``disk_full`` record for ``path`` (deduped
    process-wide: repeats on the same path are dropped). Returns whether
    a record was written — callers use it to avoid double-logging."""
    key = str(path)
    with _noted_lock:
        if key in _noted_paths:
            return False
        _noted_paths.add(key)
    get_journal().event("disk_full", path=key, op=str(op))
    return True


def reset_disk_full_notes() -> None:
    """Forget the dedup set (tests / a driver that verified space was
    actually freed and wants the next exhaustion journaled afresh)."""
    with _noted_lock:
        _noted_paths.clear()


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def backoff_delays(retries: int, base_s: float = 0.05, max_s: float = 2.0,
                   jitter: float = 0.5, rng=None) -> list[float]:
    """The sleep schedule for ``retries`` retry attempts.

    Delay ``i`` is uniform in ``[b_i, b_i*(1+jitter)]`` with
    ``b_i = min(base_s * 2**i, max_s)``: exponential growth, a hard
    per-delay cap, and enough spread that a gang of preempted workers
    does not hammer a recovering filesystem in lockstep."""
    draw = rng.random if rng is not None else random.random
    out = []
    for i in range(max(0, int(retries))):
        b = min(base_s * (2.0 ** i), max_s)
        out.append(b * (1.0 + jitter * draw()) if jitter > 0 else b)
    return out


def retry_call(fn, *args, retries: int | None = None,
               base_s: float | None = None, max_s: float = 2.0,
               jitter: float = 0.5, retry_on=(OSError,), what: str = "",
               rng=None, sleep=time.sleep, **kwargs):
    """Call ``fn(*args, **kwargs)``; retry transient failures.

    ``retries`` / ``base_s`` default from ``MXNET_TPU_RETRIES`` (2) and
    ``MXNET_TPU_RETRY_BASE_S`` (0.05 s) so drivers can tune the whole
    package's patience without code changes. The final failure re-raises
    the last exception; intermediate ones are journaled."""
    if retries is None:
        retries = _env_int("MXNET_TPU_RETRIES", 2)
    if base_s is None:
        base_s = _env_float("MXNET_TPU_RETRY_BASE_S", 0.05)
    delays = backoff_delays(retries, base_s, max_s, jitter, rng)
    what = what or getattr(fn, "__name__", "call")
    for attempt, delay in enumerate([*delays, None]):
        try:
            return fn(*args, **kwargs)
        except retry_on as exc:
            if is_disk_full(exc):
                # exhaustion, not a transient: retrying burns the whole
                # budget against a full disk and delays the temp-file
                # cleanup that actually frees space
                note_disk_full(getattr(exc, "filename", None) or what,
                               op=what)
                raise
            if delay is None:
                raise
            get_journal().event(
                "retry", what=what, attempt=attempt + 1,
                retries=retries, delay_s=round(delay, 4),
                error=type(exc).__name__, detail=str(exc)[:200])
            sleep(delay)

"""mx.monitor — training-time tensor inspection.

ref: python/mxnet/monitor.py Monitor (installed via
Executor.SetMonitorCallback; fit(monitor=...) wires it through
module/module.py install_monitor). The engine-callback mechanism doesn't
exist under XLA — a compiled program has no per-op completion events — so
this Monitor asks the executor to return pattern-matched intermediates as
extra program outputs instead (symbol.py _make_eval_fn capture_re), which
costs output bandwidth only on the batches where ``tic()`` activates it.
"""
from __future__ import annotations

import logging
import math
import re

from . import ndarray as nd

__all__ = ["Monitor"]


class Monitor:
    """Collects statistics of pattern-matched intermediate outputs (and,
    with ``monitor_all``, parameters/auxiliary states) every ``interval``
    batches::

        mon = mx.monitor.Monitor(10, pattern=".*fc.*")
        mod.fit(train_iter, num_epoch=2, monitor=mon)

    API parity with the reference Monitor: install/tic/toc/toc_print,
    ``stat_func`` defaulting to mean absolute value.

    Known divergence: ops INSIDE control-flow subgraphs (foreach /
    while_loop / cond) are not monitored — their per-iteration values
    live inside a compiled ``lax.scan`` and cannot come back as extra
    program outputs without stacking across iterations; the reference's
    per-op engine callback has no XLA equivalent there.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        if stat_func is None:
            def stat_func(x):          # ref: monitor.py asum_stat
                return nd.norm(x) / math.sqrt(x.size)
        self.stat_func = stat_func
        self.interval = int(interval)
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self._pattern_re = self.re_prog
        self.sort = sort
        self.monitor_all = bool(monitor_all)
        self.logger = logging.getLogger(__name__)

    # -- executor-facing ----------------------------------------------------
    def install(self, exe):
        """ref: Monitor.install — register an executor to watch."""
        exe.install_monitor(self)
        if exe not in self.exes:
            self.exes.append(exe)

    def _collect(self, name, array):
        """Called by the executor with each captured intermediate."""
        self.queue.append((self.step, name,
                           nd.NDArray(array, _skip_device_put=True)))

    # -- batch protocol -----------------------------------------------------
    def tic(self):
        """Start collecting if this batch is on the interval."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting; returns [(step, name, stat NDArray)]."""
        if not self.activated:
            return []
        self.activated = False
        if self.monitor_all:
            for exe in self.exes:
                for name, arr in list(exe.arg_dict.items()) + \
                        list(exe.aux_dict.items()):
                    if self.re_prog.match(name):   # same filter as outputs
                        self.queue.append((self.step, name, arr))
        res = []
        queue, self.queue = self.queue, []
        if self.sort:
            queue.sort(key=lambda t: t[1])
        for step, name, arr in queue:
            res.append((step, name, self.stat_func(arr)))
        return res

    def toc_print(self):
        """ref: Monitor.toc_print — log the collected stats."""
        for step, name, stat in self.toc():
            val = stat.asnumpy() if hasattr(stat, "asnumpy") else stat
            self.logger.info("Batch: %7d %30s %s", step, name, str(val))

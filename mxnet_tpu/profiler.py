"""``mx.profiler`` — profiling facade (ref: python/mxnet/profiler.py over
src/profiler/profiler.cc).

The reference's profiler instruments the engine's op execution and writes
chrome://tracing JSON (SURVEY §5.1). On TPU the equivalent truth source is
the XLA/JAX profiler (xplane traces viewable in TensorBoard/Perfetto,
including per-op device timing), so this facade drives ``jax.profiler``
under the reference's API: ``set_config`` + ``set_state('run'/'stop')``,
scoped ``Marker``/``scope`` (→ ``jax.profiler.TraceAnnotation`` so Gluon
block names appear on device traces), and ``dumps()`` for a host-side
aggregate table.
"""
from __future__ import annotations

import os
import time
from collections import defaultdict

from .base import MXNetError

__all__ = ["set_config", "set_state", "state", "dumps", "dump", "pause",
           "resume", "Marker", "scope", "device_stats"]

_config = {"filename": "profile.json", "profile_all": False,
           "profile_symbolic": True, "profile_imperative": True,
           "profile_memory": True, "profile_api": True,
           "aggregate_stats": False}
_state = "stop"
_trace_dir = None
_agg = defaultdict(lambda: [0, 0.0])    # name -> [count, total_sec]


def set_config(**kwargs):
    """ref: profiler.py set_config(filename=..., profile_all=...)."""
    _config.update(kwargs)


def set_state(state_name="stop", profile_process="worker"):
    """'run' starts a JAX profiler trace; 'stop' ends it. The trace
    directory derives from the configured filename."""
    global _state, _trace_dir
    import jax
    if state_name == _state:
        return
    if state_name == "run":
        # starting a device trace is a backend touch: route it through
        # the diagnostics guard so a wedged tunnel leaves a journaled
        # breadcrumb instead of hanging the profiler silently
        from .diagnostics import guard
        guard.ensure_backend(tag="profiler-start-trace")
        base = _config.get("filename", "profile.json")
        _trace_dir = os.path.splitext(base)[0] + "_trace"
        os.makedirs(_trace_dir, exist_ok=True)
        jax.profiler.start_trace(_trace_dir)
        _state = "run"
    elif state_name == "stop":
        try:
            jax.profiler.stop_trace()
        except RuntimeError:
            pass
        _state = "stop"
    else:
        raise MXNetError(f"invalid profiler state {state_name!r}")


def state():
    return _state


def pause(profile_process="worker"):
    set_state("stop")


def resume(profile_process="worker"):
    set_state("run")


def dump(finished=True, profile_process="worker"):
    """Finish the trace and write the configured ``filename`` as
    chrome://tracing JSON (ref: profiler.cc DumpProfile — the reference
    writes profile.json in the same format; here it is converted from
    the captured xplane with xprof's trace_viewer tool). The raw xplane
    stays under <filename>_trace for TensorBoard."""
    set_state("stop")
    if not _trace_dir:
        return
    import json as _json
    try:
        from xprof.convert import raw_to_tool_data
        data, _ = raw_to_tool_data.xspace_to_tool_data(
            [_latest_xplane(_trace_dir)], "trace_viewer", {})
        if isinstance(data, bytes):
            data = data.decode()
        _json.loads(data)       # must be valid chrome-trace JSON
    except Exception as e:      # conversion unavailable: keep raw xplane
        import logging
        logging.getLogger(__name__).warning(
            "profiler.dump(): chrome-trace conversion unavailable (%s); "
            "raw xplane kept under %s", e, _trace_dir)
        return
    from .resilience.atomic import atomic_write
    with atomic_write(_config.get("filename", "profile.json"), "w") as f:
        f.write(data)


def dumps(reset=False, format="table"):
    """Host-side aggregate of Marker/scope timings (the reference's
    aggregate_stats table, ref: src/profiler/aggregate_stats.cc)."""
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
    for name, (count, total) in sorted(_agg.items()):
        avg = total / count * 1e3 if count else 0.0
        lines.append(f"{name:<40}{count:>8}{total * 1e3:>12.3f}{avg:>12.3f}")
    if reset:
        _agg.clear()
    return "\n".join(lines)


def _latest_xplane(trace_dir):
    """Newest xplane capture under ``trace_dir``."""
    import glob

    xplanes = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                        recursive=True)
    if not xplanes:
        raise MXNetError(f"no xplane capture under {trace_dir!r}; run "
                         "set_state('run') … set_state('stop') around "
                         "device work first")
    return max(xplanes, key=os.path.getmtime)


def _parse_tool_stats(trace_dir, tool="hlo_stats"):
    """Parse the newest xplane capture under ``trace_dir`` with one of
    xprof's converters (the exact pipeline the TensorBoard profile
    plugin runs). Returns a list of per-op dicts."""
    import json

    xplane = _latest_xplane(trace_dir)
    try:
        from xprof.convert import raw_to_tool_data
    except ImportError as e:                          # pragma: no cover
        raise MXNetError("device_stats needs the xprof package "
                         "(tensorboard profile plugin)") from e
    data, _ = raw_to_tool_data.xspace_to_tool_data([xplane], tool, {})
    j = json.loads(data if isinstance(data, str) else data.decode())
    if isinstance(j, list):                # framework_op_stats wraps in []
        j = j[0]
    cols = [c["label"] for c in j["cols"]]
    rows = []
    for r in j["rows"]:
        rows.append({label: (cell.get("v") if cell else None)
                     for label, cell in zip(cols, r["c"])})
    return rows


def _parse_hlo_stats(trace_dir):
    return _parse_tool_stats(trace_dir, "hlo_stats")


def _load_xplane_pb2():
    """Load the XSpace protobuf bindings standalone (the generated module
    only needs google.protobuf — importing it by path avoids pulling the
    whole tensorflow package in)."""
    import importlib.util
    import glob as _glob
    import sysconfig
    for root in {sysconfig.get_paths()["purelib"],
                 sysconfig.get_paths().get("platlib", "")}:
        hits = _glob.glob(os.path.join(
            root, "**", "profiler", "protobuf", "xplane_pb2.py"),
            recursive=True)
        if hits:
            spec = importlib.util.spec_from_file_location(
                "mxnet_tpu._xplane_pb2", hits[0])
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            return mod
    raise MXNetError("xplane_pb2 bindings not found")


def _parse_xplane_events(trace_dir):
    """Last-resort op stats straight from the raw xplane proto: per-op
    SELF time (nested child events subtracted stack-wise per line) over
    the device planes, or the XLA runtime line of the host plane when no
    device plane exists (XLA:CPU)."""
    pb2 = _load_xplane_pb2()
    space = pb2.XSpace()
    with open(_latest_xplane(trace_dir), "rb") as f:
        space.ParseFromString(f.read())
    planes = [p for p in space.planes if p.name.startswith("/device:")]
    if not planes:
        planes = [p for p in space.planes if p.name.startswith("/host:")
                  and any("XLA" in ln.name or "PjRt" in ln.name
                          for ln in p.lines)]
    events = defaultdict(list)      # name -> [(dur_ps, children_ps_box)]
    for plane in planes:
        md = plane.event_metadata
        for line in plane.lines:
            if not ("XLA" in line.name or "PjRt" in line.name
                    or plane.name.startswith("/device:")):
                continue
            evs = sorted(line.events, key=lambda e: (e.offset_ps,
                                                     -e.duration_ps))
            stack = []                        # (end_ps, children_ps_box)
            for e in evs:
                name = md[e.metadata_id].name
                start, dur = e.offset_ps, e.duration_ps
                while stack and stack[-1][0] <= start:
                    stack.pop()
                if name.startswith("end: "):  # paired marker, not an op
                    continue
                if stack:
                    stack[-1][1][0] += dur    # credit to parent's children
                children = [0.0]
                stack.append((start + dur, children))
                events[name].append((dur, children))
    rows = []
    for name, recs in events.items():
        self_ps = sum(dur - ch[0] for dur, ch in recs)
        rows.append({"Operation Name": name,
                     "Operation Type": name.rstrip("0123456789.")
                     or name,
                     "Total self-time (us)": max(self_ps, 0.0) / 1e6,
                     "#Occurrences": len(recs),
                     "Bound by": ""})
    return rows


def device_stats(trace_dir=None, top=20):
    """Per-HLO-op device-time table from the last captured trace — the
    TPU analog of the reference profiler's per-operator stats (ref:
    src/profiler/aggregate_stats.cc; here the truth source is the
    hardware xplane, aggregated per HLO category with self time and HBM
    traffic). Returns the formatted table string.

    Usage::

        mx.profiler.set_state('run')
        train_step(...)            # device work
        mx.profiler.set_state('stop')
        print(mx.profiler.device_stats())
    """
    tdir = trace_dir or _trace_dir or "."

    def num(row, label):
        v = row.get(label)
        try:
            return float(v)
        except (TypeError, ValueError):
            return 0.0

    rows = _parse_hlo_stats(tdir)
    if rows:                        # TPU/GPU: full per-HLO device stats
        time_col, name_col, cat_col = ("Total self time (us)",
                                       "HLO op name", "HLO op category")
        header = "HLO category"
    else:
        # XLA:CPU emits no HLO device plane for the stats tools — read
        # the raw xplane (XLA runtime events, nesting-corrected self
        # time). framework_op_stats is tried first in case a backend
        # serves it without hlo_stats; any converter failure falls
        # through to the raw-xplane tier.
        try:
            fw = _parse_tool_stats(tdir, "framework_op_stats")
        except Exception:
            fw = []
        rows = [r for r in fw if r.get("Operation Type") != "IDLE"
                and num(r, "Total self-time (us)") > 0]
        if not rows:
            rows = _parse_xplane_events(tdir)
        time_col, name_col, cat_col = ("Total self-time (us)",
                                       "Operation Name", "Operation Type")
        header = "framework op type"

    cats = defaultdict(lambda: [0.0, 0.0, 0])
    total = 0.0
    for r in rows:
        t = num(r, time_col)
        gb = num(r, "HBM BW (GiB/s)") * (t / 1e6) * 1.073741824
        c = cats[r.get(cat_col) or "uncategorized"]
        c[0] += t
        c[1] += gb
        c[2] += int(num(r, "#Occurrences") or 1)
        total += t
    lines = [f"{header:<28}{'self ms':>10}{'HBM GB':>9}"
             f"{'%time':>7}{'ops':>6}"]
    for name, (t, gb, n) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        pct = 100.0 * t / total if total else 0.0
        lines.append(f"{name:<28}{t / 1e3:>10.3f}{gb:>9.2f}"
                     f"{pct:>7.1f}{n:>6}")
    lines.append(f"{'TOTAL':<28}{total / 1e3:>10.3f}")
    lines.append("")
    lines.append(f"top {top} ops by self time:")
    by_time = sorted(rows, key=lambda r: -num(r, time_col))
    for r in by_time[:top]:
        t = num(r, time_col)
        lines.append(f"  {t / 1e3:>9.3f} ms  "
                     f"{(r.get('Bound by') or ''):<12}"
                     f"{(r.get(name_col) or '')[:60]}")
    return "\n".join(lines)


class Marker:
    """Scoped annotation: host-side aggregate timing + device-trace
    annotation (ref: profiler.py Marker / mx.profiler.scope)."""

    def __init__(self, name, scope_name="<unk>"):
        self.name = name
        self._ann = None
        self._t0 = None

    def __enter__(self):
        import jax
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        entry = _agg[self.name]
        entry[0] += 1
        entry[1] += dt
        self._ann.__exit__(*exc)

    # one-shot API parity (ref: Marker.mark)
    def mark(self, scope_name="process"):
        entry = _agg[self.name]
        entry[0] += 1


def scope(name="<unk>:"):
    return Marker(name)

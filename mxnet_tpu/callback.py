"""Training callbacks (ref: python/mxnet/callback.py)."""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric",
           "LogValidationMetricsCallback", "module_checkpoint"]


class Speedometer:
    """Logs samples/sec every ``frequent`` batches (ref: callback.py
    Speedometer — the throughput number BASELINE.md's protocol reads)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.monotonic() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(msg, param.epoch, count, speed,
                                 *sum(name_value, ()))
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.monotonic()
        else:
            self.init = True
            self.tic = time.monotonic()


def do_checkpoint(prefix, period=1, keep_last=None):
    """Epoch-end checkpointing callback (ref: callback.py do_checkpoint).

    Saves ride the atomic path (tmp + fsync + rename — a preemption
    mid-save leaves the previous epoch intact), the prefix directory is
    created if missing, and ``keep_last=k`` prunes all but the newest k
    epochs (``.params`` + ``.states``) after each save."""
    from . import model
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            model.save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
            if keep_last:
                model.gc_checkpoints(prefix, keep_last)
    return _callback


module_checkpoint = do_checkpoint


def log_train_metric(period, auto_reset=False):
    """ref: callback.py log_train_metric."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class LogValidationMetricsCallback:
    """ref: callback.py LogValidationMetricsCallback."""

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)

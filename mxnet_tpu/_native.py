"""ctypes bindings for the native runtime (native/libmxtpu.so).

The reference's runtime substrate is C++ (dmlc-core recordio, the
ThreadedEngine); this build keeps those components native and binds them
with ctypes (no pybind11 in the image — SURVEY environment notes). The
library builds lazily with g++ on first use and is cached; everything has
a pure-Python fallback so the package works without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_pkg_dir = os.path.dirname(os.path.abspath(__file__))
_here = os.path.dirname(_pkg_dir)
_native_dir = os.path.join(_here, "native")
# binary-wheel installs ship the library inside the package (setup.py
# build_py hook); editable installs build it in the repo's native/ dir
_wheel_lib = os.path.join(_pkg_dir, "libmxtpu.so")
_lib_path = _wheel_lib if os.path.exists(_wheel_lib) \
    else os.path.join(_native_dir, "libmxtpu.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    srcs = [os.path.join(_native_dir, f)
            for f in ("recordio.cc", "engine.cc", "predict.cc")]
    if not all(os.path.exists(s) for s in srcs):
        return False
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
             "-o", _lib_path] + srcs,
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # stale iff older than ANY source (a predict.cc-only edit must
        # rebuild too — comparing just one file shipped a stale .so)
        srcs = [os.path.join(_native_dir, f)
                for f in ("recordio.cc", "engine.cc", "predict.cc")]
        srcs = [s for s in srcs if os.path.exists(s)]
        if not os.path.exists(_lib_path) or (
                srcs and os.path.getmtime(_lib_path)
                < max(os.path.getmtime(s) for s in srcs)):
            # init-once: the lock exists to make every other thread
            # wait for the one-time deadlined build
            # graftlint: disable=G15 init-once build serializer
            if not _build() and not os.path.exists(_lib_path):
                return None
        try:
            lib = ctypes.CDLL(_lib_path)
        except OSError:
            return None
        # recordio
        lib.mxio_writer_open.restype = ctypes.c_void_p
        lib.mxio_writer_open.argtypes = [ctypes.c_char_p]
        lib.mxio_writer_write.restype = ctypes.c_int
        lib.mxio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_uint64]
        lib.mxio_writer_tell.restype = ctypes.c_int64
        lib.mxio_writer_tell.argtypes = [ctypes.c_void_p]
        lib.mxio_writer_close.argtypes = [ctypes.c_void_p]
        lib.mxio_reader_open.restype = ctypes.c_void_p
        lib.mxio_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.mxio_reader_seek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.mxio_reader_next.restype = ctypes.c_int
        lib.mxio_reader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.mxio_reader_close.argtypes = [ctypes.c_void_p]
        # engine
        lib.mxengine_create.restype = ctypes.c_void_p
        lib.mxengine_create.argtypes = [ctypes.c_int]
        lib.mxengine_destroy.argtypes = [ctypes.c_void_p]
        lib.mxengine_new_var.restype = ctypes.c_uint64
        lib.mxengine_new_var.argtypes = [ctypes.c_void_p]
        lib.mxengine_push.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
        lib.mxengine_wait_all.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


ENGINE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class NativeReader:
    """Sequential native RecordIO reader, optionally with background
    prefetch (prefetch_depth > 0 — the ThreadedIter analog)."""

    def __init__(self, path, prefetch_depth=0):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.mxio_reader_open(path.encode(),
                                             int(prefetch_depth))
        if not self._h:
            raise IOError(f"cannot open {path}")

    def seek(self, pos):
        self._lib.mxio_reader_seek(self._h, pos)

    def read(self):
        data = ctypes.c_char_p()
        length = ctypes.c_uint64()
        r = self._lib.mxio_reader_next(self._h, ctypes.byref(data),
                                       ctypes.byref(length))
        if r == 0:
            return None
        if r < 0:
            raise IOError("corrupt recordio stream")
        return ctypes.string_at(data, length.value)

    def close(self):
        if self._h:
            self._lib.mxio_reader_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


class NativeWriter:
    def __init__(self, path):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.mxio_writer_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path} for writing")

    def write(self, buf):
        if self._lib.mxio_writer_write(self._h, buf, len(buf)) != 0:
            raise IOError("recordio write failed")

    def tell(self):
        return self._lib.mxio_writer_tell(self._h)

    def close(self):
        if self._h:
            self._lib.mxio_writer_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


class NativeEngine:
    """The ThreadedEngine facade: push host tasks with read/write var
    deps; the C++ scheduler runs them race-free on a thread pool."""

    def __init__(self, num_workers=4):
        self._lib = get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.mxengine_create(num_workers)
        self._keep = []          # keep callback trampolines alive

    def new_var(self):
        return self._lib.mxengine_new_var(self._h)

    def push(self, fn, read_vars=(), write_vars=()):
        cb = ENGINE_CB(lambda _arg, f=fn: f())
        self._keep.append(cb)
        r = (ctypes.c_uint64 * len(read_vars))(*read_vars)
        w = (ctypes.c_uint64 * len(write_vars))(*write_vars)
        self._lib.mxengine_push(
            self._h, ctypes.cast(cb, ctypes.c_void_p), None,
            r, len(read_vars), w, len(write_vars))

    def wait_all(self):
        self._lib.mxengine_wait_all(self._h)
        self._keep.clear()

    def close(self):
        if self._h:
            self._lib.mxengine_destroy(self._h)
            self._h = None

    def __del__(self):
        self.close()

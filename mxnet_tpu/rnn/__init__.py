"""``mx.rnn`` — the legacy (pre-Gluon) RNN API surface (ref:
python/mxnet/rnn/: rnn_cell.py, io.py BucketSentenceIter,
rnn.py save/load_rnn_checkpoint).

The cell classes are the SAME objects as ``gluon.rnn``'s — the reference
deprecated this module in favor of Gluon and kept the cells
behavior-identical; here one implementation serves both names (cells are
HybridBlocks, so ``unroll`` composes in eager, hybridized, and symbolic
programs alike). ``BucketSentenceIter`` is the bucketing data iterator
the Module-API RNN examples train from (pairs with
``mx.mod.BucketingModule``).
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from .. import ndarray as nd
from ..base import MXNetError
from ..gluon.rnn import (BidirectionalCell, DropoutCell, GRUCell,
                         LSTMCell, RecurrentCell, ResidualCell, RNNCell,
                         SequentialRNNCell, ZoneoutCell)
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
           "BidirectionalCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "RecurrentCell", "BucketSentenceIter",
           "encode_sentences"]

BaseRNNCell = RecurrentCell   # the reference's base-class name


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Map tokenized sentences to integer ids, growing ``vocab``
    (ref: rnn/io.py encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
        if vocab:
            idx = max(max(vocab.values()) + 1, idx)
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    if unknown_token is None:
                        raise MXNetError(f"unknown token {word!r} with "
                                         "a frozen vocab")
                    word = unknown_token
                    if word not in vocab:
                        # never grow a frozen vocab: a fresh id would
                        # land past the embedding the caller sized to it
                        raise MXNetError(
                            f"unknown_token {word!r} must already be in "
                            "the provided vocab")
                else:
                    vocab[word] = idx
                    idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketing iterator over variable-length encoded sentences
    (ref: rnn/io.py BucketSentenceIter): each sentence lands in the
    smallest bucket that fits, batches come from one bucket at a time
    with ``bucket_key`` set so BucketingModule picks the right-shaped
    program."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if buckets is None:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size] or [max(len(s)
                                                  for s in sentences)]
        buckets = sorted(buckets)
        self.data = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            buck = np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype) for x in self.data]
        if ndiscard:
            import logging
            logging.warning("discarded %d sentences longer than the "
                            "largest bucket", ndiscard)
        self.buckets = buckets
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.layout = layout
        self.default_bucket_key = max(buckets)
        shape = ((batch_size, self.default_bucket_key)
                 if layout == "NT" else (self.default_bucket_key,
                                         batch_size))
        self.provide_data = [DataDesc(data_name, shape)]
        self.provide_label = [DataDesc(label_name, shape)]
        self.reset()

    def reset(self):
        self.curr_idx = 0
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend((i, j) for j in
                            range(0, len(buck) - self.batch_size + 1,
                                  self.batch_size))
        _pyrandom.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        # next-token labels derive AFTER the shuffle so rows stay aligned
        self.label = []
        for buck in self.data:
            lab = np.empty_like(buck)
            if buck.size:
                lab[:, :-1] = buck[:, 1:]
                lab[:, -1] = self.invalid_label
            self.label.append(lab)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.data[i][j:j + self.batch_size]
        label = self.label[i][j:j + self.batch_size]
        if self.layout == "TN":
            data, label = data.T, label.T
        return DataBatch(data=[nd.array(data)], label=[nd.array(label)],
                         bucket_key=self.buckets[i], pad=0,
                         provide_data=[DataDesc(self.data_name,
                                                data.shape)],
                         provide_label=[DataDesc(self.label_name,
                                                 label.shape)])

// Native RecordIO reader/writer + threaded prefetcher.
//
// TPU-native equivalent of the reference's C++ I/O substrate:
//  - framing: 3rdparty/dmlc-core/include/dmlc/recordio.h (kMagic, cflag in
//    the upper 3 bits of lrec, 4-byte alignment, multi-part splitting when
//    the payload contains the magic word)
//  - prefetch: src/io/iter_prefetcher.h ThreadedIter (bounded queue filled
//    by a background thread so host decode overlaps device compute)
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image); the
// Python layer (mxnet_tpu/recordio.py) transparently uses this when built.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

inline uint32_t EncodeLRec(uint32_t cflag, uint32_t length) {
  return (cflag << 29U) | length;
}
inline uint32_t DecodeFlag(uint32_t rec) { return (rec >> 29U) & 7U; }
inline uint32_t DecodeLength(uint32_t rec) {
  return rec & ((1U << 29U) - 1U);
}

class Writer {
 public:
  explicit Writer(const char* path) : fp_(std::fopen(path, "wb")) {}
  ~Writer() {
    if (fp_) std::fclose(fp_);
  }
  bool ok() const { return fp_ != nullptr; }

  // dmlc RecordIOWriter::WriteRecord: split payload at 4-byte-aligned
  // occurrences of the magic word.
  bool Write(const char* data, size_t size) {
    if (!fp_) return false;
    std::vector<size_t> splits;
    for (size_t off = 0; off + 4 <= size; off += 4) {
      uint32_t word;
      std::memcpy(&word, data + off, 4);
      if (word == kMagic) splits.push_back(off);
    }
    std::vector<std::pair<size_t, size_t>> parts;  // (start, len)
    size_t start = 0;
    for (size_t off : splits) {
      parts.emplace_back(start, off - start);
      start = off + 4;
    }
    parts.emplace_back(start, size - start);
    const size_t n = parts.size();
    for (size_t i = 0; i < n; ++i) {
      uint32_t cflag = 0;
      if (n > 1) cflag = (i == 0) ? 1 : (i == n - 1 ? 3 : 2);
      uint32_t len = static_cast<uint32_t>(parts[i].second);
      uint32_t lrec = EncodeLRec(cflag, len);
      if (std::fwrite(&kMagic, 4, 1, fp_) != 1) return false;
      if (std::fwrite(&lrec, 4, 1, fp_) != 1) return false;
      if (len && std::fwrite(data + parts[i].first, 1, len, fp_) != len)
        return false;
      static const char pad_bytes[4] = {0, 0, 0, 0};
      size_t pad = (4 - len % 4) % 4;
      if (pad && std::fwrite(pad_bytes, 1, pad, fp_) != pad) return false;
    }
    return true;
  }

  int64_t Tell() const { return fp_ ? std::ftell(fp_) : -1; }

 private:
  std::FILE* fp_;
};

class Reader {
 public:
  explicit Reader(const char* path) : fp_(std::fopen(path, "rb")) {}
  ~Reader() {
    if (fp_) std::fclose(fp_);
  }
  bool ok() const { return fp_ != nullptr; }

  void Seek(int64_t pos) {
    if (fp_) std::fseek(fp_, static_cast<long>(pos), SEEK_SET);
  }

  // Returns: 1 record read into out, 0 EOF, -1 corrupt stream.
  int Read(std::string* out) {
    out->clear();
    uint32_t flag = 0;
    bool multi = false;
    while (true) {
      uint32_t magic, lrec;
      if (std::fread(&magic, 4, 1, fp_) != 1) return multi ? -1 : 0;
      if (magic != kMagic) return -1;
      if (std::fread(&lrec, 4, 1, fp_) != 1) return -1;
      flag = DecodeFlag(lrec);
      uint32_t len = DecodeLength(lrec);
      size_t base = out->size();
      if (multi) {
        const char* m = reinterpret_cast<const char*>(&kMagic);
        out->append(m, 4);  // re-insert the split-out magic
        base = out->size();
      }
      out->resize(base + len);
      if (len && std::fread(&(*out)[base], 1, len, fp_) != len) return -1;
      size_t pad = (4 - len % 4) % 4;
      if (pad) std::fseek(fp_, static_cast<long>(pad), SEEK_CUR);
      if (flag == 0 || flag == 3) return 1;
      if (flag == 2 && !multi) return -1;
      multi = true;
    }
  }

 private:
  std::FILE* fp_;
};

// Bounded-queue background prefetcher (ThreadedIter analog).
class Prefetcher {
 public:
  Prefetcher(const char* path, size_t depth)
      : reader_(path), depth_(depth ? depth : 4), done_(false), error_(false) {
    if (reader_.ok())
      worker_ = std::thread([this] { Run(); });
    else
      done_ = true;
  }
  ~Prefetcher() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
      cv_space_.notify_all();
    }
    if (worker_.joinable()) worker_.join();
  }
  bool ok() const { return reader_.ok(); }

  // 1 ok, 0 eof, -1 error
  int Next(std::string* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_data_.wait(lk, [this] { return !queue_.empty() || done_; });
    if (queue_.empty()) return error_ ? -1 : 0;
    *out = std::move(queue_.front());
    queue_.pop_front();
    cv_space_.notify_one();
    return 1;
  }

 private:
  void Run() {
    std::string rec;
    while (true) {
      int r = reader_.Read(&rec);
      std::unique_lock<std::mutex> lk(mu_);
      if (r != 1) {
        error_ = (r < 0);
        done_ = true;
        cv_data_.notify_all();
        return;
      }
      cv_space_.wait(lk, [this] { return queue_.size() < depth_ || stop_; });
      if (stop_) return;
      queue_.push_back(std::move(rec));
      cv_data_.notify_one();
    }
  }

  Reader reader_;
  size_t depth_;
  std::deque<std::string> queue_;
  std::mutex mu_;
  std::condition_variable cv_data_, cv_space_;
  std::thread worker_;
  bool done_, error_, stop_ = false;
};

struct ReadHandle {
  Reader* reader = nullptr;
  Prefetcher* prefetcher = nullptr;
  std::string last;
};

}  // namespace

extern "C" {

void* mxio_writer_open(const char* path) {
  auto* w = new Writer(path);
  if (!w->ok()) {
    delete w;
    return nullptr;
  }
  return w;
}

int mxio_writer_write(void* handle, const char* data, uint64_t size) {
  return static_cast<Writer*>(handle)->Write(data, size) ? 0 : -1;
}

int64_t mxio_writer_tell(void* handle) {
  return static_cast<Writer*>(handle)->Tell();
}

void mxio_writer_close(void* handle) { delete static_cast<Writer*>(handle); }

void* mxio_reader_open(const char* path, int prefetch_depth) {
  auto* h = new ReadHandle();
  if (prefetch_depth > 0) {
    h->prefetcher = new Prefetcher(path, prefetch_depth);
    if (!h->prefetcher->ok()) {
      delete h->prefetcher;
      delete h;
      return nullptr;
    }
  } else {
    h->reader = new Reader(path);
    if (!h->reader->ok()) {
      delete h->reader;
      delete h;
      return nullptr;
    }
  }
  return h;
}

void mxio_reader_seek(void* handle, int64_t pos) {
  auto* h = static_cast<ReadHandle*>(handle);
  if (h->reader) h->reader->Seek(pos);
}

// 1 ok (data/len valid until next call), 0 eof, -1 error
int mxio_reader_next(void* handle, const char** data, uint64_t* len) {
  auto* h = static_cast<ReadHandle*>(handle);
  int r = h->prefetcher ? h->prefetcher->Next(&h->last)
                        : h->reader->Read(&h->last);
  if (r == 1) {
    *data = h->last.data();
    *len = h->last.size();
  }
  return r;
}

void mxio_reader_close(void* handle) {
  auto* h = static_cast<ReadHandle*>(handle);
  delete h->prefetcher;
  delete h->reader;
  delete h;
}

}  // extern "C"

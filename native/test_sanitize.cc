// Sanitizer stress driver for the native runtime (SURVEY §5.2 — the
// reference runs its C++ core under ASAN/TSAN in CI via
// ci/docker/runtime_functions.sh sanitizer builds; this is the
// mxnet_tpu analog, a pure-native binary so the sanitizers see every
// frame without Python interposition).
//
// Built and run by ci/run_tests.sh sanitize as
//   g++ -fsanitize=address,undefined ... test_sanitize.cc engine.cc \
//       recordio.cc predict.cc
//   g++ -fsanitize=thread ...           (same sources)
//
// Exercises, from many threads where it matters:
//   1. the var-dependency engine: RAW/WAR/WAW chains must serialize per
//      var while independent chains overlap (ordering asserted with
//      per-chain sequence counters — a data race here is exactly what
//      TSAN exists to catch);
//   2. RecordIO writer → threaded prefetching reader round trip;
//   3. the predict API error paths (malformed model JSON / bad handles).
#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void* mxengine_create(int num_workers);
void mxengine_destroy(void* e);
uint64_t mxengine_new_var(void* e);
void mxengine_push(void* e, void (*fn)(void*), void* arg,
                   const uint64_t* reads, int n_reads,
                   const uint64_t* writes, int n_writes);
void mxengine_wait_all(void* e);

void* mxio_writer_open(const char* path);
int mxio_writer_write(void* handle, const char* data, uint64_t size);
int64_t mxio_writer_tell(void* handle);
void mxio_writer_close(void* handle);
void* mxio_reader_open(const char* path, int prefetch_depth);
int mxio_reader_next(void* handle, const char** data, uint64_t* len);
void mxio_reader_close(void* handle);

int MXPredCreate(const char* symbol_json, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 unsigned num_input_nodes, const char** input_keys,
                 const unsigned* input_shape_indptr,
                 const unsigned* input_shape_data, void** out);
const char* MXPredGetLastError();
int MXPredFree(void* handle);
}

// ---- 1. engine ordering under load ---------------------------------------

struct ChainTask {
  std::atomic<int>* counter;  // per-chain sequence counter
  int expect;                 // value the counter must hold when we run
  std::atomic<int>* errors;
};

static void chain_fn(void* arg) {
  auto* t = static_cast<ChainTask*>(arg);
  // if WAW ordering is broken two tasks of one chain run out of order
  // (or concurrently — TSAN flags the racing increments)
  int seen = t->counter->load(std::memory_order_relaxed);
  if (seen != t->expect) t->errors->fetch_add(1);
  t->counter->fetch_add(1);
}

static void engine_stress() {
  constexpr int kChains = 16;
  constexpr int kLen = 200;
  void* eng = mxengine_create(4);
  std::atomic<int> counters[kChains];
  std::atomic<int> errors{0};
  std::vector<uint64_t> vars(kChains);
  std::vector<ChainTask> tasks;
  tasks.reserve(kChains * kLen);
  for (int c = 0; c < kChains; ++c) {
    counters[c] = 0;
    vars[c] = mxengine_new_var(eng);
  }
  for (int i = 0; i < kLen; ++i) {
    for (int c = 0; c < kChains; ++c) {
      tasks.push_back({&counters[c], i, &errors});
      // each task WRITES its chain var -> strict serialization per chain
      mxengine_push(eng, chain_fn, &tasks.back(), nullptr, 0, &vars[c], 1);
    }
  }
  // cross-chain RAW fan-in: one reader of every var runs after all writes
  struct Fin {
    std::atomic<int>* counters;
    std::atomic<int>* errors;
  } fin{counters, &errors};
  mxengine_push(
      eng,
      [](void* a) {
        auto* f = static_cast<Fin*>(a);
        for (int c = 0; c < kChains; ++c)
          if (f->counters[c].load() != kLen) f->errors->fetch_add(1);
      },
      &fin, vars.data(), kChains, nullptr, 0);
  mxengine_wait_all(eng);
  mxengine_destroy(eng);
  assert(errors.load() == 0 && "engine ordering violated");
  for (int c = 0; c < kChains; ++c) assert(counters[c].load() == kLen);
  std::printf("engine_stress ok\n");
}

// ---- 2. recordio round trip (threaded prefetcher) ------------------------

static void recordio_roundtrip(const char* path) {
  constexpr int kRecords = 500;
  void* w = mxio_writer_open(path);
  assert(w);
  for (int i = 0; i < kRecords; ++i) {
    std::string payload(17 + (i % 61), static_cast<char>('a' + i % 26));
    payload += std::to_string(i);
    assert(mxio_writer_write(w, payload.data(), payload.size()) == 0);
  }
  assert(mxio_writer_tell(w) > 0);
  mxio_writer_close(w);

  for (int prefetch : {0, 4}) {  // plain reader and threaded prefetcher
    void* r = mxio_reader_open(path, prefetch);
    assert(r);
    int n = 0;
    const char* data;
    uint64_t len;
    int rc;
    while ((rc = mxio_reader_next(r, &data, &len)) == 1) {
      std::string payload(17 + (n % 61), static_cast<char>('a' + n % 26));
      payload += std::to_string(n);
      assert(len == payload.size() && memcmp(data, payload.data(), len) == 0);
      ++n;
    }
    assert(rc == 0 && n == kRecords);
    mxio_reader_close(r);
  }
  std::remove(path);
  std::printf("recordio_roundtrip ok\n");
}

// ---- 3. predict API error paths ------------------------------------------

static void predict_errors() {
  void* h = nullptr;
  const char* keys[] = {"data"};
  unsigned indptr[] = {0, 2};
  unsigned shape[] = {1, 3};
  int rc = MXPredCreate("{not json", nullptr, 0, 1, 0, 1, keys, indptr,
                        shape, &h);
  assert(rc != 0 && h == nullptr);
  assert(MXPredGetLastError() != nullptr &&
         MXPredGetLastError()[0] != '\0');
  std::printf("predict_errors ok\n");
}

int main(int argc, char** argv) {
  // rec path from argv so concurrent CI runs don't collide in /tmp
  std::string rec = argc > 1 ? std::string(argv[1])
                             : "/tmp/mxtpu_sanitize_test.rec";
  engine_stress();
  recordio_roundtrip(rec.c_str());
  predict_errors();
  std::printf("SANITIZE PASS\n");
  return 0;
}

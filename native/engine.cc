// Var-dependency task engine — the reference's ThreadedEngine design
// (ref: src/engine/threaded_engine.{h,cc}, threaded_engine_perdevice.cc)
// re-scoped for the TPU build: XLA/PjRt already dataflow-orders device
// compute, so the native engine's remaining job is HOST-side work — decode,
// augment, pack, checkpoint IO — scheduled race-free by declared var deps.
//
// Semantics (the reference's Engine::PushAsync contract):
//  - an op declares const (read) vars and mutable (write) vars;
//  - a read waits on the latest pending write of each read var; a write
//    waits on every pending op of each written var (RAW/WAR/WAW ordering;
//    concurrent readers allowed);
//  - worker threads drain the ready queue; WaitForAll blocks the caller.
//
// Scheduling uses explicit reverse edges resolved at push time: each
// blocker records its dependents and decrements them on completion — the
// same bookkeeping as ThreadedVar::CompleteReadDependency /
// CompleteWriteDependency, flattened.
//
// C ABI for ctypes; callbacks are C function pointers (Python passes
// CFUNCTYPE trampolines — used for IO-bound work where the GIL releases).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Callback = void (*)(void*);

struct Opr {
  Callback fn;
  void* arg;
  std::vector<uint64_t> read_vars;
  std::vector<uint64_t> write_vars;
  std::vector<Opr*> dependents;   // ops whose wait_count includes me
  int wait_count = 0;
  bool completed = false;
};

struct Var {
  std::deque<std::pair<Opr*, bool>> pending;  // (op, is_write), FIFO
};

class Engine {
 public:
  explicit Engine(int num_workers) {
    if (num_workers <= 0) num_workers = 2;
    for (int i = 0; i < num_workers; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~Engine() {
    WaitForAll();
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
      cv_ready_.notify_all();
    }
    for (auto& t : workers_) t.join();
  }

  uint64_t NewVar() {
    std::lock_guard<std::mutex> lk(mu_);
    uint64_t id = next_var_++;
    vars_.emplace(id, Var{});
    return id;
  }

  void Push(Callback fn, void* arg, const uint64_t* reads, int n_reads,
            const uint64_t* writes, int n_writes) {
    auto* op = new Opr();
    op->fn = fn;
    op->arg = arg;
    op->read_vars.assign(reads, reads + n_reads);
    op->write_vars.assign(writes, writes + n_writes);
    std::lock_guard<std::mutex> lk(mu_);
    ++outstanding_;
    int blockers = 0;
    for (uint64_t v : op->read_vars) {
      auto& q = vars_[v].pending;
      for (auto it = q.rbegin(); it != q.rend(); ++it) {
        if (it->second) {               // latest pending write
          it->first->dependents.push_back(op);
          ++blockers;
          break;
        }
      }
      q.emplace_back(op, false);
    }
    for (uint64_t v : op->write_vars) {
      auto& q = vars_[v].pending;
      for (auto& entry : q) {           // every pending op
        entry.first->dependents.push_back(op);
        ++blockers;
      }
      q.emplace_back(op, true);
    }
    op->wait_count = blockers;
    if (blockers == 0) {
      ready_.push_back(op);
      cv_ready_.notify_one();
    }
  }

  void WaitForAll() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_done_.wait(lk, [this] { return outstanding_ == 0; });
  }

 private:
  void WorkerLoop() {
    while (true) {
      Opr* op;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_ready_.wait(lk, [this] { return stop_ || !ready_.empty(); });
        if (stop_ && ready_.empty()) return;
        op = ready_.front();
        ready_.pop_front();
      }
      op->fn(op->arg);
      Complete(op);
    }
  }

  void Complete(Opr* op) {
    std::lock_guard<std::mutex> lk(mu_);
    for (Opr* dep : op->dependents) {
      if (--dep->wait_count == 0) {
        ready_.push_back(dep);
        cv_ready_.notify_one();
      }
    }
    auto erase_from = [op](std::deque<std::pair<Opr*, bool>>& q) {
      for (auto it = q.begin(); it != q.end(); ++it) {
        if (it->first == op) {
          q.erase(it);
          break;
        }
      }
    };
    for (uint64_t v : op->read_vars) erase_from(vars_[v].pending);
    for (uint64_t v : op->write_vars) erase_from(vars_[v].pending);
    --outstanding_;
    if (outstanding_ == 0) cv_done_.notify_all();
    delete op;
  }

  std::mutex mu_;
  std::condition_variable cv_ready_, cv_done_;
  std::deque<Opr*> ready_;
  std::unordered_map<uint64_t, Var> vars_;
  std::vector<std::thread> workers_;
  uint64_t next_var_ = 1;
  bool stop_ = false;
  int outstanding_ = 0;
};

}  // namespace

extern "C" {

void* mxengine_create(int num_workers) { return new Engine(num_workers); }

void mxengine_destroy(void* e) { delete static_cast<Engine*>(e); }

uint64_t mxengine_new_var(void* e) {
  return static_cast<Engine*>(e)->NewVar();
}

void mxengine_push(void* e, void (*fn)(void*), void* arg,
                   const uint64_t* reads, int n_reads,
                   const uint64_t* writes, int n_writes) {
  static_cast<Engine*>(e)->Push(fn, arg, reads, n_reads, writes, n_writes);
}

void mxengine_wait_all(void* e) { static_cast<Engine*>(e)->WaitForAll(); }

}  // extern "C"

// C++ inference API over the C predict ABI — the cpp-package analog
// (ref: cpp-package/include/mxnet-cpp + the reference's predict-cpp
// example): RAII Predictor with exceptions, std::vector I/O, move
// semantics. Header-only; link against libmxtpu.so.
//
//   mxnet_tpu::Predictor p("model-symbol.json", "model-0000.params",
//                          {{"data", {8, 784}}});
//   p.set_input("data", batch);         // std::vector<float>
//   p.forward();
//   std::vector<float> out = p.get_output(0);
#pragma once

#include <cstdint>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

extern "C" {
int MXPredCreate(const char* symbol_json, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 unsigned num_input_nodes, const char** input_keys,
                 const unsigned* input_shape_indptr,
                 const unsigned* input_shape_data, void** out);
int MXPredSetInput(void* handle, const char* key, const float* data,
                   unsigned size);
int MXPredForward(void* handle);
int MXPredGetOutputShape(void* handle, unsigned index, long* shape,
                         unsigned* ndim);
int MXPredGetOutput(void* handle, unsigned index, float* data,
                    unsigned size);
int MXPredFree(void* handle);
const char* MXPredGetLastError(void);
}

namespace mxnet_tpu {

class Error : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

namespace detail {
inline void check(int rc, const char* what) {
  if (rc != 0) {
    const char* msg = MXPredGetLastError();
    throw Error(std::string(what) + ": " +
                (msg && msg[0] ? msg : "unknown error"));
  }
}

inline std::string read_file(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw Error("cannot open " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}
}  // namespace detail

class Predictor {
 public:
  using Shape = std::vector<unsigned>;

  // Load from exported files (net.export(prefix) writes
  // prefix-symbol.json + prefix-0000.params).
  Predictor(const std::string& symbol_path, const std::string& param_path,
            const std::vector<std::pair<std::string, Shape>>& inputs)
      : Predictor(detail::read_file(symbol_path),
                  detail::read_file(param_path), inputs, true) {}

  // Load from in-memory buffers.
  Predictor(const std::string& symbol_json, const std::string& params,
            const std::vector<std::pair<std::string, Shape>>& inputs,
            bool /*from_memory*/)
  {
    std::vector<const char*> keys;
    std::vector<unsigned> indptr{0};
    std::vector<unsigned> dims;
    for (const auto& kv : inputs) {
      keys.push_back(kv.first.c_str());
      dims.insert(dims.end(), kv.second.begin(), kv.second.end());
      indptr.push_back(static_cast<unsigned>(dims.size()));
    }
    detail::check(
        MXPredCreate(symbol_json.c_str(), params.data(),
                     static_cast<int>(params.size()), /*dev_type=*/1,
                     /*dev_id=*/0,
                     static_cast<unsigned>(inputs.size()),
                     keys.empty() ? nullptr : keys.data(),
                     indptr.data(), dims.empty() ? nullptr : dims.data(),
                     &handle_),
        "MXPredCreate");
  }

  Predictor(Predictor&& other) noexcept : handle_(other.handle_) {
    other.handle_ = nullptr;
  }
  Predictor& operator=(Predictor&& other) noexcept {
    if (this != &other) {
      reset();
      handle_ = other.handle_;
      other.handle_ = nullptr;
    }
    return *this;
  }
  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;
  ~Predictor() { reset(); }

  void set_input(const std::string& key, const std::vector<float>& data) {
    detail::check(MXPredSetInput(handle_, key.c_str(), data.data(),
                                 static_cast<unsigned>(data.size())),
                  "MXPredSetInput");
  }

  void forward() { detail::check(MXPredForward(handle_), "MXPredForward"); }

  std::vector<long> output_shape(unsigned index = 0) const {
    unsigned ndim = 0;   // query ndim first (the ABI allows nullptr)
    detail::check(MXPredGetOutputShape(handle_, index, nullptr, &ndim),
                  "MXPredGetOutputShape");
    std::vector<long> shape(ndim);
    if (ndim)
      detail::check(MXPredGetOutputShape(handle_, index, shape.data(),
                                         &ndim),
                    "MXPredGetOutputShape");
    return shape;
  }

  std::vector<float> get_output(unsigned index = 0) const {
    auto shape = output_shape(index);
    std::size_t n = 1;
    for (long d : shape) n *= static_cast<std::size_t>(d);
    std::vector<float> out(n);
    detail::check(MXPredGetOutput(handle_, index, out.data(),
                                  static_cast<unsigned>(n)),
                  "MXPredGetOutput");
    return out;
  }

 private:
  void reset() {
    if (handle_) {
      MXPredFree(handle_);
      handle_ = nullptr;
    }
  }
  void* handle_ = nullptr;
};

}  // namespace mxnet_tpu

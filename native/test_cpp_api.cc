// C++ client of the RAII inference API (native/mxnet_tpu.hpp — the
// cpp-package analog). Loads an exported model, classifies a raw float
// batch, prints argmax per row; also exercises move semantics and the
// exception error path. Built and run by tests/test_predict_api.py.
#include <cstdio>
#include <cstdlib>
#include <utility>

#include "mxnet_tpu.hpp"

int main(int argc, char** argv) {
  if (argc < 6) {
    std::fprintf(stderr,
                 "usage: %s sym.json model.params input.f32 batch dim\n",
                 argv[0]);
    return 2;
  }
  const unsigned batch = static_cast<unsigned>(std::atoi(argv[4]));
  const unsigned dim = static_cast<unsigned>(std::atoi(argv[5]));

  // exception path: malformed model must throw, not crash
  try {
    mxnet_tpu::Predictor bad("{not json", "junk",
                             {{"data", {1u, dim}}}, true);
    std::fprintf(stderr, "malformed model did not throw\n");
    return 1;
  } catch (const mxnet_tpu::Error&) {
  }

  mxnet_tpu::Predictor built(argv[1], argv[2], {{"data", {batch, dim}}});
  mxnet_tpu::Predictor p(std::move(built));   // move ctor keeps handle

  std::vector<float> input(static_cast<std::size_t>(batch) * dim);
  {
    std::FILE* f = std::fopen(argv[3], "rb");
    if (!f || std::fread(input.data(), sizeof(float), input.size(), f)
                  != input.size()) {
      std::fprintf(stderr, "cannot read %s\n", argv[3]);
      return 1;
    }
    std::fclose(f);
  }
  p.set_input("data", input);
  p.forward();
  const auto shape = p.output_shape(0);
  if (shape.size() != 2 || shape[0] != static_cast<long>(batch)) {
    std::fprintf(stderr, "unexpected output shape\n");
    return 1;
  }
  const auto out = p.get_output(0);
  const long classes = shape[1];
  for (unsigned b = 0; b < batch; ++b) {
    long best = 0;
    for (long c = 1; c < classes; ++c)
      if (out[b * classes + c] > out[b * classes + best]) best = c;
    std::printf("%ld\n", best);
  }
  return 0;
}

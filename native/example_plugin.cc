// Example dynamic op library for mxnet_tpu.library.load — the lib_api.h
// analog (ref: example/extensions/lib_custom_op in the reference). Builds
// standalone: g++ -shared -fPIC -o libexample_plugin.so example_plugin.cc
#include <cmath>

extern "C" {

int mxtpu_plugin_op_count(void) { return 2; }

const char* mxtpu_plugin_op_name(int i) {
  return i == 0 ? "plugin_gelu_tanh" : "plugin_mish";
}

int mxtpu_plugin_op_compute(int i, const float* x, float* y, long n) {
  if (i == 0) {
    for (long j = 0; j < n; ++j) {
      float v = x[j];
      y[j] = 0.5f * v *
             (1.f + std::tanh(0.7978845608f * (v + 0.044715f * v * v * v)));
    }
    return 0;
  }
  if (i == 1) {
    for (long j = 0; j < n; ++j) {
      float v = x[j];
      y[j] = v * std::tanh(std::log1p(std::exp(v)));
    }
    return 0;
  }
  return 1;
}

}  // extern "C"

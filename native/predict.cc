// C predict API — standalone native inference over an exported
// `-symbol.json` + `.params` pair, no Python dependency
// (ref: src/c_api/c_predict_api.cc MXPredCreate/SetInput/Forward/
// GetOutput/Free; the reference drives the full C++ runtime, here a
// self-contained CPU graph interpreter covers the deployment path the
// reference's amalgamation/mobile builds serve).
//
// Supported ops: Convolution, FullyConnected, BatchNorm (inference),
// Activation, Pooling, Flatten, Reshape, elemwise/broadcast
// add/mul/sub/div, scalar ops, Concat, softmax, log_softmax, Dropout
// (identity), LeakyReLU (leaky/elu/gelu), Embedding, LayerNorm,
// fused self/cross attention, transpose, batch_dot, slice/slice_like,
// expand_dims, squeeze — the exported-model op sets of the model zoo's
// image classifiers (LeNet/MLP/ResNet/VGG) AND the transformer family
// (BERT encoder, Sockeye-style NMT transformer).
//
// Build: part of libmxtpu.so (see Makefile). C ABI mirrors the
// reference's signatures.

#include <algorithm>
#include <cctype>
#include <climits>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace predict {

// ---------------------------------------------------------------------------
// minimal JSON parser (objects, arrays, strings, numbers, bool, null)
// ---------------------------------------------------------------------------
struct JValue {
  enum Kind { OBJ, ARR, STR, NUM, BOOL, NUL } kind = NUL;
  std::map<std::string, JValue> obj;
  std::vector<JValue> arr;
  std::string str;
  double num = 0;
  bool b = false;
  const JValue& operator[](const std::string& k) const {
    static JValue nul;
    auto it = obj.find(k);
    return it == obj.end() ? nul : it->second;
  }
};

struct JParser {
  const char* p;
  const char* end;
  explicit JParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}
  void skip() { while (p < end && std::isspace((unsigned char)*p)) ++p; }
  [[noreturn]] void fail(const char* msg) {
    throw std::runtime_error(std::string("json: ") + msg);
  }
  JValue parse() { skip(); return value(); }
  JValue value() {
    skip();
    if (p >= end) fail("eof");
    switch (*p) {
      case '{': return object();
      case '[': return array();
      case '"': { JValue v; v.kind = JValue::STR; v.str = string(); return v; }
      case 't': p += 4; { JValue v; v.kind = JValue::BOOL; v.b = true; return v; }
      case 'f': p += 5; { JValue v; v.kind = JValue::BOOL; v.b = false; return v; }
      case 'n': p += 4; return JValue{};
      default: return number();
    }
  }
  JValue object() {
    JValue v; v.kind = JValue::OBJ; ++p;  // '{'
    skip();
    if (p < end && *p == '}') { ++p; return v; }
    while (true) {
      skip();
      std::string key = string();
      skip();
      if (p >= end || *p != ':') fail("expected :");
      ++p;
      v.obj[key] = value();
      skip();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; break; }
      fail("expected , or }");
    }
    return v;
  }
  JValue array() {
    JValue v; v.kind = JValue::ARR; ++p;  // '['
    skip();
    if (p < end && *p == ']') { ++p; return v; }
    while (true) {
      v.arr.push_back(value());
      skip();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; break; }
      fail("expected , or ]");
    }
    return v;
  }
  std::string string() {
    if (*p != '"') fail("expected string");
    ++p;
    std::string out;
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) {
        ++p;
        switch (*p) {
          case 'n': out += '\n'; break;
          case 't': out += '\t'; break;
          case 'r': out += '\r'; break;
          case 'u': p += 4; out += '?'; break;  // no unicode in our files
          default: out += *p;
        }
      } else {
        out += *p;
      }
      ++p;
    }
    ++p;
    return out;
  }
  JValue number() {
    char* np = nullptr;
    JValue v; v.kind = JValue::NUM;
    v.num = std::strtod(p, &np);
    if (np == p) fail("bad number");
    p = np;
    return v;
  }
};

// ---------------------------------------------------------------------------
// attr parsing (python-repr strings: "(3, 3)", "64", "True", "relu")
// ---------------------------------------------------------------------------
static std::vector<long> parse_tuple(const std::string& s) {
  std::vector<long> out;
  long cur = 0;
  bool in_num = false, neg = false;
  for (char c : s) {
    if (std::isdigit((unsigned char)c)) { cur = cur * 10 + (c - '0'); in_num = true; }
    else if (c == '-') { neg = true; }
    else if (in_num) { out.push_back(neg ? -cur : cur); cur = 0; in_num = false; neg = false; }
  }
  if (in_num) out.push_back(neg ? -cur : cur);
  return out;
}
static long parse_int(const std::string& s, long dflt) {
  if (s.empty()) return dflt;
  try { return std::stol(s); } catch (...) { return dflt; }
}
static double parse_float(const std::string& s, double dflt) {
  if (s.empty()) return dflt;
  try { return std::stod(s); } catch (...) { return dflt; }
}
static bool parse_bool(const std::string& s, bool dflt) {
  if (s == "True" || s == "true" || s == "1") return true;
  if (s == "False" || s == "false" || s == "0") return false;
  return dflt;
}

// ---------------------------------------------------------------------------
// tensors
// ---------------------------------------------------------------------------
struct Tensor {
  std::vector<long> shape;
  std::vector<float> data;
  long size() const {
    long n = 1;
    for (long s : shape) n *= s;
    return n;
  }
  void alloc() { data.assign(size(), 0.f); }
};

// ---------------------------------------------------------------------------
// .params reader (format: ndarray.py save — list magic, ndarray records,
// then names; names carry arg:/aux: prefixes). Format flag word 1 = the
// crash-consistent v3 container (docs/checkpointing.md): a CRC32 after
// every entry and a 24-byte <body_len, names_crc, reserved, magic>
// footer. This reader checks the footer's structural claim (body length
// vs buffer size — catches truncation up front) and skips the CRCs
// (the Python loader owns checksum verification; no zlib dependency
// here). Flag 0 = the reference-era layout, unchanged.
// ---------------------------------------------------------------------------
struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  Reader(const void* buf, size_t n)
      : p((const uint8_t*)buf), end((const uint8_t*)buf + n) {}
  template <typename T> T get() {
    if (p + sizeof(T) > end) throw std::runtime_error("params: truncated");
    T v;
    std::memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    return v;
  }
};

static const uint64_t kParamsFooterMagic = 0x4D58545043524333ULL;
static const size_t kParamsFooterBytes = 24;

static std::map<std::string, Tensor> load_params(const void* buf, size_t n) {
  Reader r(buf, n);
  uint64_t magic = r.get<uint64_t>();
  if (magic != 0x112) throw std::runtime_error("params: bad list magic");
  uint64_t fmt = r.get<uint64_t>();  // 0 = legacy, 1 = CRC + footer
  if (fmt > 1)
    throw std::runtime_error("params: unsupported format flag " +
                             std::to_string(fmt));
  bool crc = fmt == 1;
  if (crc) {
    if (n < 16 + kParamsFooterBytes)
      throw std::runtime_error("params: truncated (no footer)");
    const uint8_t* foot = (const uint8_t*)buf + n - kParamsFooterBytes;
    uint64_t body_len, foot_magic;
    std::memcpy(&body_len, foot, 8);
    std::memcpy(&foot_magic, foot + 16, 8);
    if (foot_magic != kParamsFooterMagic || body_len != n - kParamsFooterBytes)
      throw std::runtime_error("params: footer missing or inconsistent "
                               "(interrupted save?)");
    r.end -= kParamsFooterBytes;  // names stop before the footer
  }
  uint64_t count = r.get<uint64_t>();
  std::vector<Tensor> arrays(count);
  for (uint64_t i = 0; i < count; ++i) {
    uint32_t nd_magic = r.get<uint32_t>();
    if (nd_magic != 0xF993FAC9) throw std::runtime_error("params: bad nd magic");
    uint32_t ndim = r.get<uint32_t>();
    Tensor t;
    for (uint32_t d = 0; d < ndim; ++d) t.shape.push_back((long)r.get<int64_t>());
    r.get<int32_t>();  // dev_type
    r.get<int32_t>();  // dev_id
    int32_t dtype = r.get<int32_t>();
    long sz = t.size();
    t.data.resize(sz);
    if (dtype == 0) {  // float32
      for (long j = 0; j < sz; ++j) t.data[j] = r.get<float>();
    } else if (dtype == 1) {  // float64
      for (long j = 0; j < sz; ++j) t.data[j] = (float)r.get<double>();
    } else if (dtype == 6) {  // int64  (code table: ndarray.py _DTYPE_CODE)
      for (long j = 0; j < sz; ++j) t.data[j] = (float)r.get<int64_t>();
    } else if (dtype == 4) {  // int32
      for (long j = 0; j < sz; ++j) t.data[j] = (float)r.get<int32_t>();
    } else {
      throw std::runtime_error("params: unsupported dtype code " +
                               std::to_string(dtype));
    }
    if (crc) r.get<uint32_t>();  // per-entry CRC32 (verified Python-side)
    arrays[i] = std::move(t);
  }
  uint64_t n_names = r.get<uint64_t>();
  if (n_names > count)
    throw std::runtime_error("params: more names than arrays");
  std::map<std::string, Tensor> out;
  for (uint64_t i = 0; i < n_names; ++i) {
    uint64_t len = r.get<uint64_t>();
    if (len > (size_t)(r.end - r.p))   // no pointer arithmetic: huge len
      throw std::runtime_error("params: truncated name");
    std::string name((const char*)r.p, len);
    r.p += len;
    // strip arg:/aux: prefixes
    auto pos = name.find(':');
    if (pos != std::string::npos) name = name.substr(pos + 1);
    out[name] = std::move(arrays[i]);
  }
  return out;
}

// ---------------------------------------------------------------------------
// op kernels (NCHW, fp32, plain loops — deployment-correctness path)
// ---------------------------------------------------------------------------
static void conv2d(const Tensor& x, const Tensor& w, const Tensor* bias,
                   const std::vector<long>& stride, const std::vector<long>& pad,
                   const std::vector<long>& dilate, long groups, Tensor& out) {
  long N = x.shape[0], C = x.shape[1], H = x.shape[2], W = x.shape[3];
  long O = w.shape[0], KH = w.shape[2], KW = w.shape[3];
  long SH = stride[0], SW = stride[1], PH = pad[0], PW = pad[1];
  long DH = dilate[0], DW = dilate[1];
  long OH = (H + 2 * PH - (DH * (KH - 1) + 1)) / SH + 1;
  long OW = (W + 2 * PW - (DW * (KW - 1) + 1)) / SW + 1;
  long Cg = C / groups, Og = O / groups;
  out.shape = {N, O, OH, OW};
  out.alloc();
  for (long n = 0; n < N; ++n)
    for (long o = 0; o < O; ++o) {
      long g = o / Og;
      for (long oy = 0; oy < OH; ++oy)
        for (long ox = 0; ox < OW; ++ox) {
          float acc = bias ? bias->data[o] : 0.f;
          for (long c = 0; c < Cg; ++c)
            for (long ky = 0; ky < KH; ++ky) {
              long iy = oy * SH - PH + ky * DH;
              if (iy < 0 || iy >= H) continue;
              for (long kx = 0; kx < KW; ++kx) {
                long ix = ox * SW - PW + kx * DW;
                if (ix < 0 || ix >= W) continue;
                acc += x.data[((n * C + g * Cg + c) * H + iy) * W + ix] *
                       w.data[((o * Cg + c) * KH + ky) * KW + kx];
              }
            }
          out.data[((n * O + o) * OH + oy) * OW + ox] = acc;
        }
    }
}

static void fully_connected(const Tensor& x, const Tensor& w,
                            const Tensor* bias, bool flatten, Tensor& out) {
  long K = w.shape[1], O = w.shape[0];
  long N;
  std::vector<long> lead;
  if (flatten || x.shape.size() == 2) {
    N = x.shape[0];
    lead = {N};
  } else {
    N = x.size() / x.shape.back();
    lead.assign(x.shape.begin(), x.shape.end() - 1);
  }
  out.shape = lead;
  out.shape.push_back(O);
  out.alloc();
  for (long n = 0; n < N; ++n)
    for (long o = 0; o < O; ++o) {
      float acc = bias ? bias->data[o] : 0.f;
      const float* xr = &x.data[n * K];
      const float* wr = &w.data[o * K];
      for (long k = 0; k < K; ++k) acc += xr[k] * wr[k];
      out.data[n * O + o] = acc;
    }
}

static void batchnorm(const Tensor& x, const Tensor& gamma, const Tensor& beta,
                      const Tensor& mean, const Tensor& var, double eps,
                      bool fix_gamma, Tensor& out) {
  out.shape = x.shape;
  out.alloc();
  long C = x.shape.size() > 1 ? x.shape[1] : x.shape[0];
  long inner = 1;
  for (size_t i = 2; i < x.shape.size(); ++i) inner *= x.shape[i];
  long N = x.shape[0];
  for (long c = 0; c < C; ++c) {
    float g = fix_gamma ? 1.f : gamma.data[c];
    float inv = 1.f / std::sqrt(var.data[c] + (float)eps);
    float scale = g * inv;
    float offset = beta.data[c] - mean.data[c] * scale;
    for (long n = 0; n < N; ++n) {
      float* po = &out.data[(n * C + c) * inner];
      const float* px = &x.data[(n * C + c) * inner];
      for (long i = 0; i < inner; ++i) po[i] = px[i] * scale + offset;
    }
  }
}

static void pooling(const Tensor& x, const std::string& type, bool global_pool,
                    const std::vector<long>& kernel,
                    const std::vector<long>& stride,
                    const std::vector<long>& pad, bool ceil_mode,
                    bool count_include_pad, Tensor& out) {
  long N = x.shape[0], C = x.shape[1], H = x.shape[2], W = x.shape[3];
  if (global_pool) {
    out.shape = {N, C, 1, 1};
    out.alloc();
    for (long n = 0; n < N; ++n)
      for (long c = 0; c < C; ++c) {
        const float* px = &x.data[(n * C + c) * H * W];
        float acc = type == "max" ? -1e30f : 0.f;
        for (long i = 0; i < H * W; ++i)
          acc = type == "max" ? std::max(acc, px[i]) : acc + px[i];
        out.data[(n * C + c)] = type == "max" ? acc : acc / (float)(H * W);
      }
    return;
  }
  long KH = kernel[0], KW = kernel[1];
  long SH = stride[0], SW = stride[1], PH = pad[0], PW = pad[1];
  auto osize = [&](long in, long k, long s, long p) {
    double v = (double)(in + 2 * p - k) / s + 1;
    return (long)(ceil_mode ? std::ceil(v) : std::floor(v));
  };
  long OH = osize(H, KH, SH, PH), OW = osize(W, KW, SW, PW);
  out.shape = {N, C, OH, OW};
  out.alloc();
  for (long n = 0; n < N; ++n)
    for (long c = 0; c < C; ++c)
      for (long oy = 0; oy < OH; ++oy)
        for (long ox = 0; ox < OW; ++ox) {
          float acc = type == "max" ? -1e30f : 0.f;
          long cnt = 0;
          for (long ky = 0; ky < KH; ++ky) {
            long iy = oy * SH - PH + ky;
            if (iy < 0 || iy >= H) continue;
            for (long kx = 0; kx < KW; ++kx) {
              long ix = ox * SW - PW + kx;
              if (ix < 0 || ix >= W) continue;
              float v = x.data[((n * C + c) * H + iy) * W + ix];
              acc = type == "max" ? std::max(acc, v) : acc + v;
              ++cnt;
            }
          }
          if (type != "max")
            acc /= (float)(count_include_pad ? KH * KW : std::max(cnt, 1L));
          out.data[((n * C + c) * OH + oy) * OW + ox] = acc;
        }
}

static void softmax_rows(Tensor& t) {
  long C = t.shape.back();
  long rows = t.size() / C;
  for (long r = 0; r < rows; ++r) {
    float* p = &t.data[r * C];
    float m = *std::max_element(p, p + C);
    double s = 0;
    for (long c = 0; c < C; ++c) { p[c] = std::exp(p[c] - m); s += p[c]; }
    for (long c = 0; c < C; ++c) p[c] = (float)(p[c] / s);
  }
}

// ---- transformer-family kernels (exported BERT / NMT graphs) --------------

static void embedding(const Tensor& idx, const Tensor& w, Tensor& out) {
  long V = w.shape[0], U = w.shape[1];
  out.shape = idx.shape;
  out.shape.push_back(U);
  out.alloc();
  for (long i = 0; i < idx.size(); ++i) {
    long row = (long)std::lround(idx.data[i]);
    if (row < 0 || row >= V)
      throw std::runtime_error("Embedding index out of range");
    std::memcpy(&out.data[i * U], &w.data[row * U], U * sizeof(float));
  }
}

static void layernorm(const Tensor& x, const Tensor& gamma,
                      const Tensor& beta, double eps, long axis, Tensor& out) {
  long nd = (long)x.shape.size();
  if (axis < 0) axis += nd;
  if (axis != nd - 1)
    throw std::runtime_error("LayerNorm: only last-axis supported");
  long C = x.shape.back();
  long rows = x.size() / C;
  out.shape = x.shape;
  out.alloc();
  for (long r = 0; r < rows; ++r) {
    const float* px = &x.data[r * C];
    float* po = &out.data[r * C];
    double m = 0, v = 0;
    for (long c = 0; c < C; ++c) m += px[c];
    m /= C;
    for (long c = 0; c < C; ++c) { double d = px[c] - m; v += d * d; }
    v /= C;
    float inv = 1.f / std::sqrt((float)v + (float)eps);
    for (long c = 0; c < C; ++c)
      po[c] = (float)((px[c] - m) * inv) * gamma.data[c] + beta.data[c];
  }
}

// softmax over the last axis of a (rows, C) view of scores
static void softmax_inplace(float* p, long C) {
  float m = *std::max_element(p, p + C);
  double s = 0;
  for (long c = 0; c < C; ++c) { p[c] = std::exp(p[c] - m); s += p[c]; }
  for (long c = 0; c < C; ++c) p[c] = (float)(p[c] / s);
}

// q (B,Sq,H,D) laid flat out of proj rows; generic core shared by the fused
// self/cross attention ops (ref: the Python ops' einsum formulation,
// mxnet_tpu/ops/contrib.py _fused_self_attention/_fused_cross_attention)
static void attention_core(const float* q, const float* k, const float* v,
                           long B, long Sq, long Sk, long H, long D,
                           bool causal, float* outp) {
  float scale = 1.f / std::sqrt((float)D);
  std::vector<float> row(Sk);
  for (long b = 0; b < B; ++b)
    for (long h = 0; h < H; ++h)
      for (long i = 0; i < Sq; ++i) {
        const float* qi = &q[((b * Sq + i) * H + h) * D];
        for (long j = 0; j < Sk; ++j) {
          if (causal && j > i) { row[j] = -1e30f; continue; }
          const float* kj = &k[((b * Sk + j) * H + h) * D];
          float acc = 0;
          for (long d = 0; d < D; ++d) acc += qi[d] * kj[d];
          row[j] = acc * scale;
        }
        softmax_inplace(row.data(), Sk);
        float* oi = &outp[((b * Sq + i) * H + h) * D];
        for (long d = 0; d < D; ++d) oi[d] = 0.f;
        for (long j = 0; j < Sk; ++j) {
          const float* vj = &v[((b * Sk + j) * H + h) * D];
          float a = row[j];
          for (long d = 0; d < D; ++d) oi[d] += a * vj[d];
        }
      }
}

static void self_attention(const Tensor& qkv, long heads, bool causal,
                           Tensor& out) {
  long B = qkv.shape[0], S = qkv.shape[1], C = qkv.shape[2] / 3;
  long D = C / heads;
  // split (B,S,3C) rows into contiguous q/k/v in (B,S,H,D) flat layout
  std::vector<float> q(B * S * C), k(B * S * C), v(B * S * C);
  for (long r = 0; r < B * S; ++r) {
    const float* src = &qkv.data[r * 3 * C];
    std::memcpy(&q[r * C], src, C * sizeof(float));
    std::memcpy(&k[r * C], src + C, C * sizeof(float));
    std::memcpy(&v[r * C], src + 2 * C, C * sizeof(float));
  }
  out.shape = {B, S, C};
  out.alloc();
  attention_core(q.data(), k.data(), v.data(), B, S, S, heads, D, causal,
                 out.data.data());
}

static void cross_attention(const Tensor& qt, const Tensor& kv, long heads,
                            Tensor& out) {
  long B = qt.shape[0], Sq = qt.shape[1], C = qt.shape[2];
  long Sk = kv.shape[1], D = C / heads;
  std::vector<float> k(B * Sk * C), v(B * Sk * C);
  for (long r = 0; r < B * Sk; ++r) {
    const float* src = &kv.data[r * 2 * C];
    std::memcpy(&k[r * C], src, C * sizeof(float));
    std::memcpy(&v[r * C], src + C, C * sizeof(float));
  }
  out.shape = {B, Sq, C};
  out.alloc();
  attention_core(qt.data.data(), k.data(), v.data(), B, Sq, Sk, heads, D,
                 false, out.data.data());
}

static void transpose_nd(const Tensor& x, const std::vector<long>& axes,
                         Tensor& out) {
  long nd = (long)x.shape.size();
  std::vector<long> ax = axes;
  if (ax.empty())
    for (long i = nd - 1; i >= 0; --i) ax.push_back(i);
  out.shape.resize(nd);
  for (long i = 0; i < nd; ++i) out.shape[i] = x.shape[ax[i]];
  out.alloc();
  std::vector<long> xstride(nd, 1), ostride(nd, 1);
  for (long i = nd - 2; i >= 0; --i)
    xstride[i] = xstride[i + 1] * x.shape[i + 1];
  for (long i = nd - 2; i >= 0; --i)
    ostride[i] = ostride[i + 1] * out.shape[i + 1];
  std::vector<long> oidx(nd, 0);
  for (long o = 0; o < out.size(); ++o) {
    long rem = o, xoff = 0;
    for (long i = 0; i < nd; ++i) {
      long c = rem / ostride[i];
      rem %= ostride[i];
      xoff += c * xstride[ax[i]];
    }
    out.data[o] = x.data[xoff];
  }
}

static void batch_dot(const Tensor& a, const Tensor& b, bool ta, bool tb,
                      Tensor& out) {
  // (B.., M, K) x (B.., K, N); leading batch dims must match
  long nd = (long)a.shape.size();
  long M = ta ? a.shape[nd - 1] : a.shape[nd - 2];
  long K = ta ? a.shape[nd - 2] : a.shape[nd - 1];
  long N = tb ? b.shape[nd - 2] : b.shape[nd - 1];
  long batch = 1;
  for (long i = 0; i < nd - 2; ++i) batch *= a.shape[i];
  out.shape.assign(a.shape.begin(), a.shape.end() - 2);
  out.shape.push_back(M);
  out.shape.push_back(N);
  out.alloc();
  long as = M * K, bs = K * N;
  for (long g = 0; g < batch; ++g)
    for (long m = 0; m < M; ++m)
      for (long n2 = 0; n2 < N; ++n2) {
        float acc = 0;
        for (long kk = 0; kk < K; ++kk) {
          float av = ta ? a.data[g * as + kk * M + m]
                        : a.data[g * as + m * K + kk];
          float bv = tb ? b.data[g * bs + n2 * K + kk]
                        : b.data[g * bs + kk * N + n2];
          acc += av * bv;
        }
        out.data[(g * M + m) * N + n2] = acc;
      }
}

// numpy-style broadcast binary: op 0 add, 1 mul, 2 sub, 3 div
static void broadcast_binary(const Tensor& a, const Tensor& b, int op,
                             Tensor& out) {
  long nd = (long)std::max(a.shape.size(), b.shape.size());
  std::vector<long> sa(nd, 1), sb(nd, 1);
  std::copy(a.shape.begin(), a.shape.end(),
            sa.begin() + (nd - a.shape.size()));
  std::copy(b.shape.begin(), b.shape.end(),
            sb.begin() + (nd - b.shape.size()));
  out.shape.resize(nd);
  for (long i = 0; i < nd; ++i) {
    if (sa[i] != sb[i] && sa[i] != 1 && sb[i] != 1)
      throw std::runtime_error("broadcast shape mismatch");
    out.shape[i] = std::max(sa[i], sb[i]);
  }
  out.alloc();
  std::vector<long> so(nd, 1), ca(nd, 1), cb(nd, 1);
  for (long i = nd - 2; i >= 0; --i) {
    ca[i] = ca[i + 1] * sa[i + 1];
    cb[i] = cb[i + 1] * sb[i + 1];
    so[i] = so[i + 1] * out.shape[i + 1];
  }
  for (long o = 0; o < out.size(); ++o) {
    long rem = o, ia = 0, ib = 0;
    for (long i = 0; i < nd; ++i) {
      long c = rem / so[i];
      rem %= so[i];
      ia += (sa[i] == 1 ? 0 : c) * ca[i];
      ib += (sb[i] == 1 ? 0 : c) * cb[i];
    }
    float x = a.data[ia], y = b.data[ib];
    out.data[o] = op == 0 ? x + y : op == 1 ? x * y
                  : op == 2 ? x - y : x / y;
  }
}

// tuple parser that keeps None entries as LONG_MIN sentinels (for slice)
static const long kNone = LONG_MIN;
static std::vector<long> parse_tuple_opt(const std::string& s) {
  std::vector<long> out;
  long cur = 0;
  bool in_num = false, neg = false;
  for (size_t i = 0; i < s.size(); ++i) {
    char c = s[i];
    if (c == 'N') { out.push_back(kNone); }
    else if (std::isdigit((unsigned char)c)) {
      cur = cur * 10 + (c - '0');
      in_num = true;
    } else if (c == '-') {
      neg = true;
    } else if (in_num) {
      out.push_back(neg ? -cur : cur);
      cur = 0; in_num = false; neg = false;
    }
  }
  if (in_num) out.push_back(neg ? -cur : cur);
  return out;
}

static void slice_ranges(const Tensor& x, const std::vector<long>& begin,
                         const std::vector<long>& end, Tensor& out) {
  long nd = (long)x.shape.size();
  std::vector<long> b(nd, 0), e(x.shape);
  for (size_t i = 0; i < begin.size() && (long)i < nd; ++i) {
    if (begin[i] != kNone)
      b[i] = begin[i] < 0 ? begin[i] + x.shape[i] : begin[i];
    if (i < end.size() && end[i] != kNone)
      e[i] = end[i] < 0 ? end[i] + x.shape[i] : std::min(end[i], x.shape[i]);
  }
  out.shape.resize(nd);
  for (long i = 0; i < nd; ++i) out.shape[i] = e[i] - b[i];
  out.alloc();
  std::vector<long> xs(nd, 1), os(nd, 1);
  for (long i = nd - 2; i >= 0; --i) {
    xs[i] = xs[i + 1] * x.shape[i + 1];
    os[i] = os[i + 1] * out.shape[i + 1];
  }
  for (long o = 0; o < out.size(); ++o) {
    long rem = o, xoff = 0;
    for (long i = 0; i < nd; ++i) {
      long c = rem / os[i];
      rem %= os[i];
      xoff += (c + b[i]) * xs[i];
    }
    out.data[o] = x.data[xoff];
  }
}

// ---------------------------------------------------------------------------
// the graph executor
// ---------------------------------------------------------------------------
struct Node {
  std::string op, name;
  std::map<std::string, std::string> attrs;
  std::vector<std::pair<long, long>> inputs;  // (node_id, out_index)
};

struct Predictor {
  std::vector<Node> nodes;
  std::vector<std::pair<long, long>> heads;
  std::map<std::string, Tensor> params;
  std::map<std::string, long> var_nodes;          // name -> node id
  std::vector<std::vector<Tensor>> values;        // per node outputs
  std::vector<Tensor> inputs_by_node;             // bound inputs
  std::vector<Tensor> outputs;
  std::string last_error;

  void load_graph(const std::string& json) {
    JParser parser(json);
    JValue root = parser.parse();
    const JValue& jnodes = root["nodes"];
    for (const JValue& jn : jnodes.arr) {
      Node n;
      n.op = jn["op"].str;
      n.name = jn["name"].str;
      for (auto& kv : jn["attrs"].obj) n.attrs[kv.first] = kv.second.str;
      for (const JValue& ji : jn["inputs"].arr)
        n.inputs.push_back({(long)ji.arr[0].num, (long)ji.arr[1].num});
      if (n.op == "null") var_nodes[n.name] = (long)nodes.size();
      nodes.push_back(std::move(n));
    }
    for (const JValue& jh : root["heads"].arr)
      heads.push_back({(long)jh.arr[0].num, (long)jh.arr[1].num});
    values.resize(nodes.size());
  }

  void set_input(const std::string& name, const float* data,
                 const std::vector<long>& shape) {
    auto it = var_nodes.find(name);
    if (it == var_nodes.end())
      throw std::runtime_error("unknown input " + name);
    Tensor t;
    t.shape = shape;
    t.data.assign(data, data + t.size());
    values[it->second] = {std::move(t)};
  }

  const Tensor& in(const Node& n, size_t i) {
    auto [nid, oi] = n.inputs[i];
    if (values[nid].empty())
      throw std::runtime_error("node input not computed for " + n.name);
    if (oi >= (long)values[nid].size())
      throw std::runtime_error("output index " + std::to_string(oi) +
                               " out of range for node feeding " + n.name);
    return values[nid][oi];
  }

  void forward() {
    // bind parameters into variable nodes
    for (auto& [name, nid] : var_nodes) {
      if (!values[nid].empty()) continue;  // user-set input
      auto it = params.find(name);
      if (it == params.end())
        throw std::runtime_error("unbound variable " + name +
                                 " (not an input, not in params)");
      values[nid] = {it->second};
    }
    for (size_t id = 0; id < nodes.size(); ++id) {
      Node& n = nodes[id];
      if (n.op == "null") continue;
      Tensor out;
      auto a = [&](const char* k) {
        auto it = n.attrs.find(k);
        return it == n.attrs.end() ? std::string() : it->second;
      };
      if (n.op == "Convolution") {
        auto kernel = parse_tuple(a("kernel"));
        auto stride = a("stride").empty() ? std::vector<long>{1, 1}
                                          : parse_tuple(a("stride"));
        auto pad = a("pad").empty() ? std::vector<long>{0, 0}
                                    : parse_tuple(a("pad"));
        auto dilate = a("dilate").empty() ? std::vector<long>{1, 1}
                                          : parse_tuple(a("dilate"));
        bool no_bias = parse_bool(a("no_bias"), false);
        conv2d(in(n, 0), in(n, 1), no_bias ? nullptr : &in(n, 2), stride,
               pad, dilate, parse_int(a("num_group"), 1), out);
      } else if (n.op == "FullyConnected") {
        bool no_bias = parse_bool(a("no_bias"), false);
        fully_connected(in(n, 0), in(n, 1),
                        no_bias ? nullptr : &in(n, 2),
                        parse_bool(a("flatten"), true), out);
      } else if (n.op == "BatchNorm") {
        batchnorm(in(n, 0), in(n, 1), in(n, 2), in(n, 3), in(n, 4),
                  parse_float(a("eps"), 1e-3),
                  parse_bool(a("fix_gamma"), true), out);
        values[id] = {out, in(n, 3), in(n, 4)};
        continue;
      } else if (n.op == "Activation") {
        out = in(n, 0);
        std::string act = a("act_type");
        for (float& v : out.data) {
          if (act == "relu") v = std::max(v, 0.f);
          else if (act == "sigmoid") v = 1.f / (1.f + std::exp(-v));
          else if (act == "tanh") v = std::tanh(v);
          else if (act == "softrelu") v = std::log1p(std::exp(v));
          else throw std::runtime_error("activation " + act);
        }
      } else if (n.op == "relu") {
        out = in(n, 0);
        for (float& v : out.data) v = std::max(v, 0.f);
      } else if (n.op == "LeakyReLU") {
        out = in(n, 0);
        float slope = (float)parse_float(a("slope"), 0.25);
        std::string act = a("act_type");
        if (act.empty()) act = "leaky";
        for (float& v : out.data) {
          if (act == "leaky") v = v > 0 ? v : slope * v;
          else if (act == "elu") v = v > 0 ? v : slope * std::expm1(v);
          else if (act == "gelu")   // exact erf form, like jax.nn.gelu
            v = 0.5f * v * (1.f + std::erf(v * 0.70710678f));
          else throw std::runtime_error("LeakyReLU act_type " + act);
        }
      } else if (n.op == "Pooling") {
        auto kernel = a("kernel").empty() ? std::vector<long>{1, 1}
                                          : parse_tuple(a("kernel"));
        if (kernel.size() == 1) kernel.push_back(kernel[0]);
        auto stride = a("stride").empty() ? std::vector<long>{1, 1}
                                          : parse_tuple(a("stride"));
        if (stride.size() == 1) stride.push_back(stride[0]);
        auto pad = a("pad").empty() ? std::vector<long>{0, 0}
                                    : parse_tuple(a("pad"));
        if (pad.size() == 1) pad.push_back(pad[0]);
        pooling(in(n, 0), a("pool_type").empty() ? "max" : a("pool_type"),
                parse_bool(a("global_pool"), false), kernel, stride, pad,
                a("pooling_convention") == "full",
                parse_bool(a("count_include_pad"), true), out);
      } else if (n.op == "Flatten") {
        out = in(n, 0);
        long n0 = out.shape[0];
        out.shape = {n0, out.size() / n0};
      } else if (n.op == "reshape" || n.op == "Reshape") {
        out = in(n, 0);
        auto shape = parse_tuple(a("shape"));
        long known = 1, infer = -1;
        for (size_t i = 0; i < shape.size(); ++i) {
          if (shape[i] == -1) infer = (long)i;
          else if (shape[i] == 0) { shape[i] = out.shape[i]; known *= shape[i]; }
          else known *= shape[i];
        }
        if (infer >= 0) shape[infer] = out.size() / known;
        out.shape.assign(shape.begin(), shape.end());
      } else if (n.op == "elemwise_add" || n.op == "broadcast_add" ||
                 n.op == "elemwise_mul" || n.op == "broadcast_mul" ||
                 n.op == "elemwise_sub" || n.op == "broadcast_sub" ||
                 n.op == "elemwise_div" || n.op == "broadcast_div") {
        int kind = n.op.find("add") != std::string::npos ? 0
                   : n.op.find("mul") != std::string::npos ? 1
                   : n.op.find("sub") != std::string::npos ? 2 : 3;
        broadcast_binary(in(n, 0), in(n, 1), kind, out);
      } else if (n.op == "_mul_scalar" || n.op == "_plus_scalar" ||
                 n.op == "_minus_scalar" || n.op == "_rminus_scalar" ||
                 n.op == "_div_scalar" || n.op == "_rdiv_scalar") {
        out = in(n, 0);
        float s = (float)parse_float(a("scalar"), 0.0);
        for (float& v : out.data) {
          if (n.op == "_mul_scalar") v *= s;
          else if (n.op == "_plus_scalar") v += s;
          else if (n.op == "_minus_scalar") v -= s;
          else if (n.op == "_rminus_scalar") v = s - v;
          else if (n.op == "_div_scalar") v /= s;
          else v = s / v;
        }
      } else if (n.op == "Embedding") {
        embedding(in(n, 0), in(n, 1), out);
      } else if (n.op == "LayerNorm") {
        layernorm(in(n, 0), in(n, 1), in(n, 2),
                  parse_float(a("eps"), 1e-5), parse_int(a("axis"), -1),
                  out);
      } else if (n.op == "_contrib_fused_self_attention") {
        self_attention(in(n, 0), parse_int(a("heads"), 1),
                       parse_bool(a("causal"), false), out);
      } else if (n.op == "_contrib_fused_cross_attention") {
        cross_attention(in(n, 0), in(n, 1), parse_int(a("heads"), 1), out);
      } else if (n.op == "expand_dims") {
        out = in(n, 0);
        long ax = parse_int(a("axis"), 0);
        if (ax < 0) ax += (long)out.shape.size() + 1;
        out.shape.insert(out.shape.begin() + ax, 1);
      } else if (n.op == "squeeze") {
        out = in(n, 0);
        std::string axs = a("axis");
        if (axs.empty() || axs == "None") {
          std::vector<long> ns;
          for (long s : out.shape) if (s != 1) ns.push_back(s);
          if (ns.empty()) ns.push_back(1);
          out.shape = ns;
        } else {
          auto axes = parse_tuple(axs);
          std::vector<bool> drop(out.shape.size(), false);
          for (long ax : axes)
            drop[ax < 0 ? ax + (long)out.shape.size() : ax] = true;
          std::vector<long> ns;
          for (size_t i = 0; i < out.shape.size(); ++i)
            if (!drop[i]) ns.push_back(out.shape[i]);
          if (ns.empty()) ns.push_back(1);
          out.shape = ns;
        }
      } else if (n.op == "slice") {
        for (long st : parse_tuple_opt(a("step")))
          if (st != kNone && st != 1)
            throw std::runtime_error("slice: non-unit step unsupported");
        slice_ranges(in(n, 0), parse_tuple_opt(a("begin")),
                     parse_tuple_opt(a("end")), out);
      } else if (n.op == "slice_like") {
        const Tensor& x = in(n, 0);
        const Tensor& like = in(n, 1);
        std::vector<long> begin(x.shape.size(), 0);
        std::vector<long> end(x.shape.begin(), x.shape.end());
        std::string axs = a("axes");
        if (axs.empty() || axs == "None") {
          for (size_t i = 0; i < x.shape.size() && i < like.shape.size();
               ++i)
            end[i] = like.shape[i];
        } else {
          for (long ax : parse_tuple(axs)) {
            if (ax < 0) ax += (long)x.shape.size();
            end[ax] = like.shape[ax];
          }
        }
        slice_ranges(x, begin, end, out);
      } else if (n.op == "transpose") {
        out.shape.clear();
        transpose_nd(in(n, 0), a("axes").empty() ? std::vector<long>{}
                                                 : parse_tuple(a("axes")),
                     out);
      } else if (n.op == "batch_dot") {
        batch_dot(in(n, 0), in(n, 1),
                  parse_bool(a("transpose_a"), false),
                  parse_bool(a("transpose_b"), false), out);
      } else if (n.op == "Concat") {
        long dim = parse_int(a("dim"), 1);
        const Tensor& first = in(n, 0);
        out.shape = first.shape;
        long total = 0;
        for (size_t i = 0; i < n.inputs.size(); ++i) total += in(n, i).shape[dim];
        out.shape[dim] = total;
        out.alloc();
        long outer = 1, inner = 1;
        for (long d = 0; d < dim; ++d) outer *= first.shape[d];
        for (size_t d = dim + 1; d < first.shape.size(); ++d)
          inner *= first.shape[d];
        long off = 0;
        for (size_t i = 0; i < n.inputs.size(); ++i) {
          const Tensor& t = in(n, i);
          long chunk = t.shape[dim] * inner;
          for (long o = 0; o < outer; ++o)
            std::memcpy(&out.data[(o * out.shape[dim] + off) * inner],
                        &t.data[o * chunk], chunk * sizeof(float));
          off += t.shape[dim];
        }
      } else if (n.op == "softmax" || n.op == "SoftmaxOutput") {
        out = in(n, 0);
        long ax = parse_int(a("axis"), -1);
        long nd2 = (long)out.shape.size();
        if (ax != -1 && ax != nd2 - 1)
          throw std::runtime_error("softmax: only last-axis supported");
        softmax_rows(out);
      } else if (n.op == "log_softmax") {
        out = in(n, 0);
        softmax_rows(out);
        for (float& v : out.data) v = std::log(std::max(v, 1e-30f));
      } else if (n.op == "Dropout" || n.op == "identity") {
        out = in(n, 0);
      } else if (n.op == "_group") {
        // multi-output head grouping: pass every input through
        std::vector<Tensor> vals;
        for (size_t i = 0; i < n.inputs.size(); ++i) vals.push_back(in(n, i));
        values[id] = std::move(vals);
        continue;
      } else {
        throw std::runtime_error("predict: unsupported op " + n.op +
                                 " (node " + n.name + ")");
      }
      values[id] = {std::move(out)};
    }
    outputs.clear();
    for (auto [nid, oi] : heads) outputs.push_back(values[nid][oi]);
    // free intermediates, keep variables (params) for the next forward
    for (size_t id = 0; id < nodes.size(); ++id)
      if (nodes[id].op != "null") values[id].clear();
  }
};

}  // namespace predict

// ---------------------------------------------------------------------------
// C ABI (ref: include/mxnet/c_predict_api.h)
// ---------------------------------------------------------------------------
extern "C" {

typedef void* PredictorHandle;
static thread_local std::string mxpred_last_error;

const char* MXPredGetLastError() { return mxpred_last_error.c_str(); }

int MXPredCreate(const char* symbol_json, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 unsigned num_input_nodes, const char** input_keys,
                 const unsigned* input_shape_indptr,
                 const unsigned* input_shape_data, PredictorHandle* out) {
  (void)dev_type; (void)dev_id;
  try {
    auto p = std::make_unique<predict::Predictor>();
    p->load_graph(symbol_json);
    p->params = predict::load_params(param_bytes, (size_t)param_size);
    // the reference workflow passes input shapes here (c_predict_api.h):
    // seed them so MXPredSetInput works without a separate
    // MXPredSetInputShape call
    if (num_input_nodes > 0 && input_keys && input_shape_indptr &&
        input_shape_data) {
      p->inputs_by_node.resize(p->nodes.size());
      for (unsigned i = 0; i < num_input_nodes; ++i) {
        auto it = p->var_nodes.find(input_keys[i]);
        if (it == p->var_nodes.end())
          throw std::runtime_error(std::string("unknown input ") +
                                   input_keys[i]);
        predict::Tensor& t = p->inputs_by_node[it->second];
        t.shape.clear();
        for (unsigned d = input_shape_indptr[i];
             d < input_shape_indptr[i + 1]; ++d)
          t.shape.push_back((long)input_shape_data[d]);
      }
    }
    *out = p.release();
    return 0;
  } catch (const std::exception& e) {
    mxpred_last_error = e.what();
    return -1;
  }
}

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, unsigned size) {
  auto* p = (predict::Predictor*)handle;
  try {
    auto it = p->var_nodes.find(key);
    if (it == p->var_nodes.end())
      throw std::runtime_error(std::string("unknown input ") + key);
    // shape must have been provided via MXPredSetInputShape or reuse
    if (p->inputs_by_node.empty()) p->inputs_by_node.resize(p->nodes.size());
    predict::Tensor& t = p->inputs_by_node[it->second];
    if (t.shape.empty())
      throw std::runtime_error(std::string("set shape first for ") + key);
    if ((unsigned)t.size() != size)
      throw std::runtime_error("input size mismatch");
    t.data.assign(data, data + size);
    p->values[it->second] = {t};
    return 0;
  } catch (const std::exception& e) {
    mxpred_last_error = e.what();
    return -1;
  }
}

int MXPredSetInputShape(PredictorHandle handle, const char* key,
                        const long* shape, unsigned ndim) {
  auto* p = (predict::Predictor*)handle;
  try {
    auto it = p->var_nodes.find(key);
    if (it == p->var_nodes.end())
      throw std::runtime_error(std::string("unknown input ") + key);
    if (p->inputs_by_node.empty()) p->inputs_by_node.resize(p->nodes.size());
    predict::Tensor& t = p->inputs_by_node[it->second];
    t.shape.assign(shape, shape + ndim);
    return 0;
  } catch (const std::exception& e) {
    mxpred_last_error = e.what();
    return -1;
  }
}

int MXPredForward(PredictorHandle handle) {
  auto* p = (predict::Predictor*)handle;
  try {
    p->forward();
    return 0;
  } catch (const std::exception& e) {
    mxpred_last_error = e.what();
    return -1;
  }
}

int MXPredGetOutputShape(PredictorHandle handle, unsigned index,
                         long* shape_data, unsigned* ndim) {
  auto* p = (predict::Predictor*)handle;
  try {
    if (index >= p->outputs.size())
      throw std::runtime_error("output index out of range");
    const auto& s = p->outputs[index].shape;
    *ndim = (unsigned)s.size();
    if (shape_data)
      for (size_t i = 0; i < s.size(); ++i) shape_data[i] = s[i];
    return 0;
  } catch (const std::exception& e) {
    mxpred_last_error = e.what();
    return -1;
  }
}

int MXPredGetOutput(PredictorHandle handle, unsigned index, float* data,
                    unsigned size) {
  auto* p = (predict::Predictor*)handle;
  try {
    if (index >= p->outputs.size())
      throw std::runtime_error("output index out of range");
    const predict::Tensor& t = p->outputs[index];
    if ((unsigned)t.size() != size)
      throw std::runtime_error("output size mismatch");
    std::memcpy(data, t.data.data(), size * sizeof(float));
    return 0;
  } catch (const std::exception& e) {
    mxpred_last_error = e.what();
    return -1;
  }
}

int MXPredFree(PredictorHandle handle) {
  delete (predict::Predictor*)handle;
  return 0;
}

}  // extern "C"

/* C client of the predict API: loads an exported LeNet and classifies
 * digits from a raw float file; pure C, links only libmxtpu.so
 * (ref: the reference's image-classification/predict-cpp example). */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef void* PredictorHandle;
extern int MXPredCreate(const char*, const void*, int, int, int, unsigned,
                        const char**, const unsigned*, const unsigned*,
                        PredictorHandle*);
extern int MXPredSetInputShape(PredictorHandle, const char*, const long*,
                               unsigned);
extern int MXPredSetInput(PredictorHandle, const char*, const float*,
                          unsigned);
extern int MXPredForward(PredictorHandle);
extern int MXPredGetOutputShape(PredictorHandle, unsigned, long*, unsigned*);
extern int MXPredGetOutput(PredictorHandle, unsigned, float*, unsigned);
extern int MXPredFree(PredictorHandle);
extern const char* MXPredGetLastError(void);

static char* read_file(const char* path, long* size) {
  FILE* f = fopen(path, "rb");
  if (!f) { fprintf(stderr, "cannot open %s\n", path); exit(1); }
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = (char*)malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) exit(1);
  buf[*size] = 0;
  fclose(f);
  return buf;
}

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s sym.json model.params input.f32 batch\n",
            argv[0]);
    return 2;
  }
  long sym_size, param_size, in_size;
  char* sym = read_file(argv[1], &sym_size);
  char* params = read_file(argv[2], &param_size);
  char* input = read_file(argv[3], &in_size);
  long batch = atol(argv[4]);
  long feat = in_size / (long)sizeof(float) / batch;

  PredictorHandle h;
  if (MXPredCreate(sym, params, (int)param_size, 1, 0, 0, NULL, NULL, NULL,
                   &h)) {
    fprintf(stderr, "create failed: %s\n", MXPredGetLastError());
    return 1;
  }
  long shape[4] = {batch, 1, 28, 28};
  unsigned ndim = 4;
  if (feat != 784) { shape[1] = feat; ndim = 2; }
  if (MXPredSetInputShape(h, "data", shape, ndim) ||
      MXPredSetInput(h, "data", (const float*)input,
                     (unsigned)(in_size / sizeof(float))) ||
      MXPredForward(h)) {
    fprintf(stderr, "forward failed: %s\n", MXPredGetLastError());
    return 1;
  }
  long oshape[8];
  unsigned ondim;
  MXPredGetOutputShape(h, 0, oshape, &ondim);
  long osz = 1;
  for (unsigned i = 0; i < ondim; ++i) osz *= oshape[i];
  float* out = (float*)malloc(osz * sizeof(float));
  MXPredGetOutput(h, 0, out, (unsigned)osz);
  long classes = oshape[ondim - 1];
  for (long n = 0; n < batch; ++n) {
    long best = 0;
    for (long c = 1; c < classes; ++c)
      if (out[n * classes + c] > out[n * classes + best]) best = c;
    printf("%ld\n", best);
  }
  MXPredFree(h);
  free(sym); free(params); free(input); free(out);
  return 0;
}

#!/usr/bin/env python
"""BERT MXU-utilization experiment matrix (round-3 verdict #2).

Round 2 measured the BERT-base MLM step at ~42% MXU utilization on the
matmul fusions with the layout levers exhausted (einsum QKV measured
perf-neutral). The levers tried here attack GEMM shapes and epilogues:

  baseline        bert_12_768_12, vocab 30522, batch 128, seq 128
  vocab_pad       decoder/embedding padded to vocab 30528 (128-multiple)
                  — logits GEMM N-dim tiles evenly
  batch_256       batch 256: M-dim 32768 rows for every GEMM
  seq_pack        batch 64 x seq 256 (same tokens/step as baseline,
                  longer rows — fewer, larger attention GEMMs)
  remat_dots      jax.checkpoint(dots_saveable): recompute elementwise
                  chains in backward, keep matmul outputs

Each config reports samples/s with bench-style k-step scan timing (the
tunnel's ~90 ms dispatch overlapped by async back-to-back dispatches,
one hard sync, best of 3 windows).

Usage: PYTHONPATH=.:/root/.axon_site python benchmarks/bert_gemm_probe.py
       [--configs baseline vocab_pad ...]
"""
from __future__ import annotations

import argparse
import time

import numpy as np


def measure(name, batch, seq, vocab, on_tpu, remat=None, dropout=0.1,
            master_dtype=None, flatten=True):
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import bert

    if on_tpu:
        net = bert.get_bert_model(
            "bert_12_768_12", vocab_size=vocab, max_length=max(512, seq),
            dropout=dropout, use_pooler=False, use_classifier=False)
    else:
        net = bert.BERTModel(num_layers=2, units=64, hidden_size=128,
                             num_heads=4, max_length=max(128, seq),
                             vocab_size=vocab, use_pooler=False,
                             use_classifier=False)
    net.initialize(mx.init.Normal(0.02))

    class MLMWrapper(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, tokens):
            _, mlm = self.inner(tokens)
            return F.reshape(mlm, (-1, vocab)) if flatten else mlm

    class FlatCE(gluon.loss.Loss):
        amp_safe = property(lambda self: self._ce.amp_safe)

        def __init__(self):
            super().__init__(None, 0)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, pred, label):
            if flatten:
                label = F.reshape(label, (-1,))
            return self._ce(pred, label)

    mesh = parallel.make_mesh({"data": len(jax.devices())})
    trainer = parallel.ShardedTrainer(
        MLMWrapper(net), FlatCE(), "adam", {"learning_rate": 1e-4},
        mesh=mesh, compute_dtype="bfloat16" if on_tpu else None,
        remat=remat, master_dtype=master_dtype)
    toks = np.random.randint(0, min(vocab, 30000), (batch, seq))

    k = 8 if on_tpu else 2
    dispatches = 4 if on_tpu else 1
    np.asarray(trainer.run_steps(toks, toks, num_steps=k).asnumpy())
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(dispatches):
            loss = trainer.run_steps(toks, toks, num_steps=k)
        np.asarray(loss.asnumpy())
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    tokens_per_step = batch * seq
    sps128 = tokens_per_step / 128 * dispatches * k / best  # seq-128-equiv
    print(f"{name:<12} batch={batch:<4} seq={seq:<4} vocab={vocab:<6} "
          f"{best / (dispatches * k) * 1e3:8.1f} ms/step "
          f"{sps128:8.1f} samples(seq128-equiv)/s", flush=True)
    return sps128


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", nargs="+", default=None)
    args = ap.parse_args()
    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    V = 30522 if on_tpu else 512
    VP = 30528 if on_tpu else 512
    B = 128 if on_tpu else 4
    S = 128 if on_tpu else 32
    matrix = {
        "baseline": dict(batch=B, seq=S, vocab=V),
        "vocab_pad": dict(batch=B, seq=S, vocab=VP),
        "batch_256": dict(batch=2 * B, seq=S, vocab=V),
        "seq_pack": dict(batch=B // 2, seq=2 * S, vocab=V),
        "remat_dots": dict(batch=B, seq=S, vocab=V, remat="dots"),
        "no_dropout": dict(batch=B, seq=S, vocab=V, dropout=0.0),
        "bf16_master": dict(batch=B, seq=S, vocab=V,
                            master_dtype="bfloat16"),
        "loss3d": dict(batch=B, seq=S, vocab=V, flatten=False),
        "bf16m_loss3d": dict(batch=B, seq=S, vocab=V, flatten=False,
                             master_dtype="bfloat16"),
    }
    names = args.configs or list(matrix)
    print(f"platform={jax.devices()[0].platform}", flush=True)
    results = {}
    for n in names:
        results[n] = measure(n, on_tpu=on_tpu, **matrix[n])
    if "baseline" in results:
        for n, v in results.items():
            print(f"{n:<12} vs baseline: {v / results['baseline']:.3f}x")


if __name__ == "__main__":
    main()

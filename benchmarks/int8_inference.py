#!/usr/bin/env python
"""INT8 vs fp32 inference throughput on the chip (SURVEY §2 row 19's
perf story: the reference quantizes with cuDNN/MKLDNN int8 kernels;
here int8 lowers to XLA `dot_general`/conv with int32 accumulation).

Measures resnet50_v1 batch-256 inference in both precisions plus the
speedup ratio and a top-1 agreement check; prints one JSON line each.

Usage: PYTHONPATH=.:/root/.axon_site python benchmarks/int8_inference.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measure(name, fn, x, k, dispatches=4, windows=3):
    """Async back-to-back dispatches, one hard sync per window (the
    bert_gemm_probe methodology — PjRt pipelines the queue so the
    tunnel's per-dispatch latency overlaps)."""
    import jax

    xd = jax.device_put(x)
    np.asarray(fn(xd))                          # compile + warm
    best = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(dispatches * k):
            out = fn(xd)
        np.asarray(out)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    ips = x.shape[0] * dispatches * k / best / len(jax.devices())
    print(json.dumps({
        "metric": f"resnet50_infer_{name}_images_per_sec",
        "value": round(ips, 1),
        "unit": f"images/sec/chip (batch={x.shape[0]})",
        "ms_per_batch": round(best / dispatches / k * 1e3, 2)}))
    return ips


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.contrib import quantization as q
    from mxnet_tpu.gluon.model_zoo import vision

    on_tpu = jax.devices()[0].platform == "tpu"
    batch = 256 if on_tpu else 4
    size = 224 if on_tpu else 32
    k = 8 if on_tpu else 2

    net = vision.resnet50_v1() if on_tpu else \
        vision.resnet18_v1(classes=10, thumbnail=True)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, size, size).astype(np.float32)
    net(nd.array(x[:2]))                       # materialize params

    # fp32 path straight off the hybridized block's traced fn
    def run_fp32(xx):
        return net(nd.NDArray(xx))._data

    qnet = q.quantize_net(net, calib_data=[x[:64]], calib_mode="minmax")

    def run_int8(xx):
        return qnet(nd.NDArray(xx))._data

    r32 = measure("fp32", run_fp32, x, k)
    r8 = measure("int8", run_int8, x, k)
    print(json.dumps({"metric": "int8_speedup_vs_fp32",
                      "value": round(r8 / r32, 3), "unit": "x"}))
    # accuracy drift check on the same batch
    p32 = net(nd.array(x[:64])).asnumpy().argmax(1)
    p8 = qnet(nd.array(x[:64])).asnumpy().argmax(1)
    print(json.dumps({"metric": "int8_top1_agreement",
                      "value": round(float((p32 == p8).mean()), 4),
                      "unit": "fraction"}))


if __name__ == "__main__":
    main()

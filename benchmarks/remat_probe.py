#!/usr/bin/env python
"""Rematerialization / master-dtype experiment matrix for the RN50 step.

docs/perf_notes.md (round 2) measured the ResNet-50 train step as
HBM-bandwidth-bound: ~59 GB/step intrinsic traffic, MXU ~74% idle. The two
untried bandwidth levers are:

  - activation rematerialization (``ShardedTrainer(remat=...)`` →
    ``jax.checkpoint``): stop saving forward activations, recompute them in
    backward — trades idle MXU FLOPs for HBM writes+reads;
  - bf16 master weights (``master_dtype="bfloat16"``): halve the
    weight/momentum read+write traffic of the fused update.

This probe measures the full fused train step (fwd+bwd+SGD-mom update) for
each config with the same k-step-scan differencing as bench.py (the tunnel
costs ~90 ms/dispatch and block_until_ready does not sync honestly — see
docs/perf_notes.md "Measurement pitfalls").

Usage: PYTHONPATH=. python benchmarks/remat_probe.py [--batch 256]
"""
from __future__ import annotations

import argparse
import time

import numpy as np


def measure(config_name, batch, on_tpu, **trainer_kw):
    import jax
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet50_v1() if on_tpu else vision.resnet18_v1()
    net.initialize()
    mesh = parallel.make_mesh({"data": len(jax.devices())})
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        mesh=mesh, compute_dtype="bfloat16" if on_tpu else None, **trainer_kw)
    x_host = np.random.randn(batch, 3, 224 if on_tpu else 32,
                             224 if on_tpu else 32).astype(np.float32)
    y_host = np.random.randint(0, 1000, (batch,))
    # stage the batch on device ONCE: re-uploading per dispatch would
    # gate the measurement on the ~6 MB/s tunnel link
    trainer._prepare((x_host,))
    x = trainer._shard_batch_arg(x_host)
    y = trainer._shard_batch_arg(y_host)

    # bench.py's methodology: N back-to-back ASYNC dispatches of a k-step
    # scanned program, ONE hard sync at the end (dispatch latency overlaps
    # compute; only the final ~90 ms round-trip is exposed), best of 3
    # windows to filter transient tunnel stalls.
    k = 10 if on_tpu else 2
    dispatches = 8 if on_tpu else 2
    windows = 3
    np.asarray(trainer.run_steps(x, y, num_steps=k).asnumpy())   # compile
    best = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(dispatches):
            loss = trainer.run_steps(x, y, num_steps=k)
        np.asarray(loss.asnumpy())
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    per_step = best / (dispatches * k)
    img_s = batch / per_step
    print(f"{config_name:<28} {per_step * 1e3:8.1f} ms/step "
          f"{img_s:8.0f} img/s", flush=True)
    return per_step, img_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--configs", nargs="+", default=None)
    args = ap.parse_args()

    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    batch = args.batch or (256 if on_tpu else 8)
    print(f"platform={jax.devices()[0].platform} batch={batch}", flush=True)

    matrix = {
        "baseline": {},
        "remat_full": {"remat": "full"},
        "remat_dots": {"remat": "dots"},
        "bf16_master": {"master_dtype": "bfloat16"},
        "bf16_master+remat_full": {"master_dtype": "bfloat16",
                                   "remat": "full"},
    }
    names = args.configs or list(matrix)
    results = {}
    for name in names:
        results[name] = measure(name, batch, on_tpu, **matrix[name])
    base = results.get("baseline")
    if base:
        for name, (t, r) in results.items():
            print(f"{name:<28} speedup vs baseline: {base[0] / t:.3f}x")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Scaling-efficiency harness (BASELINE.md metric: per-chip throughput at
8 vs 64 chips, target ≥90%).

Runs the fused SPMD ResNet-50 step at a ladder of data-parallel mesh sizes
over the available devices and reports per-chip throughput + efficiency
relative to the smallest mesh. On a real pod slice this measures ICI
all-reduce overlap; on the CPU-device fallback it validates the harness
(numbers are not meaningful for the target).

Prints one JSON line per mesh size, then a summary line.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def measure(n_chips, batch_per_chip, steps, warmup, network, classes,
            image, bf16):
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    devices = jax.devices()[:n_chips]
    mesh = parallel.make_mesh({"data": n_chips}, devices=devices)
    net = vision.get_model(network, classes=classes)
    net.initialize(mx.init.Xavier())
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh, compute_dtype="bfloat16" if bf16 else None)
    batch = batch_per_chip * n_chips
    x_host = np.random.randn(batch, 3, image, image).astype(np.float32)
    y_host = np.random.randint(0, classes, (batch,))
    trainer._prepare((x_host,))
    x = trainer._shard_batch_arg(x_host)
    y = trainer._shard_batch_arg(y_host)
    for _ in range(warmup):
        trainer.step(x, y).wait_to_read()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(x, y)
    loss.wait_to_read()
    dt = time.perf_counter() - t0
    return batch * steps / dt / n_chips


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet50_v1")
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--image", type=int, default=224)
    p.add_argument("--batch-per-chip", type=int, default=128)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--sizes", default=None,
                   help="comma list of mesh sizes (default: 1,2,4,… up to "
                        "visible devices)")
    p.add_argument("--no-bf16", dest="bf16", action="store_false",
                   default=True)
    args = p.parse_args()

    import jax
    n = len(jax.devices())
    if args.sizes:
        sizes = [int(s) for s in args.sizes.split(",")]
    else:
        sizes = [s for s in (1, 2, 4, 8, 16, 32, 64) if s <= n]
    results = {}
    for s in sizes:
        per_chip = measure(s, args.batch_per_chip, args.steps, args.warmup,
                           args.network, args.classes, args.image,
                           args.bf16)
        results[s] = per_chip
        print(json.dumps({"chips": s,
                          "images_per_sec_per_chip": round(per_chip, 2)}))
    base = results[sizes[0]]
    print(json.dumps({
        "metric": "scaling_efficiency",
        "base_chips": sizes[0], "max_chips": sizes[-1],
        "value": round(results[sizes[-1]] / base, 4),
        "target": 0.9,
    }))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Scaling-efficiency harness (BASELINE.md metric: per-chip throughput at
8 vs 64 chips, target ≥90%) — wedge-proof.

Same artifact contract as bench.py (the round-5 lesson: a driver gate
must always receive ONE parseable JSON line, even when the TPU tunnel
is wedged):

1. the parent probes the backend through ``diagnostics.guard`` under a
   hard deadline and emits a structured diagnostic instead of hanging;
2. the measurement body runs in a deadlined child (``--body``); the
   parent validates the child's metric line actually parses before
   reprinting it (a dying tunnel truncating a write must be a skipped
   line, never a broken contract);
3. journal breadcrumbs + a SIGTERM finalizer emit a ``killed`` artifact
   if the outer kill lands first;
4. ``--artifact PATH`` additionally writes the full result —
   per-mesh-size throughput ladder, scaling efficiency, **elastic /
   cohort metadata** (``elastic.elastic_metadata()``: world shape, the
   MXTPU_* env wiring) and the ``observability.snapshot()`` compile/
   step-phase provenance — as a ``MULTICHIP_*.json`` document, so the
   8→64 measurement is one command on the next healthy hardware window
   (BASELINE.md staged command):

     PYTHONPATH=. python benchmarks/scaling.py --network resnet50_v1 \
         --sizes 8,64 --artifact MULTICHIP_r06.json

On the CPU-device fallback the harness validates end to end (numbers
are not meaningful for the target).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

METRIC = "scaling_efficiency"
BODY_TIMEOUT_S = 1500.0
BODY_TIMEOUT_CPU_S = 420.0


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _diagnostic(error: str, detail: str) -> dict:
    return {"metric": METRIC, "value": None, "target": 0.9,
            "error": error, "detail": detail}


def _write_artifact(path, doc) -> None:
    if not path:
        return
    from mxnet_tpu.resilience import atomic
    with atomic.atomic_write(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    print(f"scaling: artifact -> {path}", file=sys.stderr)


def measure(n_chips, batch_per_chip, steps, warmup, network, classes,
            image, bf16):
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    devices = jax.devices()[:n_chips]
    mesh = parallel.make_mesh({"data": n_chips}, devices=devices)
    net = vision.get_model(network, classes=classes)
    net.initialize(mx.init.Xavier())
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh, compute_dtype="bfloat16" if bf16 else None)
    batch = batch_per_chip * n_chips
    x_host = np.random.randn(batch, 3, image, image).astype(np.float32)
    y_host = np.random.randint(0, classes, (batch,))
    trainer._prepare((x_host,))
    x = trainer._shard_batch_arg(x_host)
    y = trainer._shard_batch_arg(y_host)
    for _ in range(warmup):
        trainer.step(x, y).wait_to_read()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(x, y)
    loss.wait_to_read()
    dt = time.perf_counter() - t0
    return batch * steps / dt / n_chips


def _parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="resnet50_v1")
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--image", type=int, default=224)
    p.add_argument("--batch-per-chip", type=int, default=128)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--sizes", default=None,
                   help="comma list of mesh sizes (default: 1,2,4,… up to "
                        "visible devices)")
    p.add_argument("--no-bf16", dest="bf16", action="store_false",
                   default=True)
    p.add_argument("--artifact", default=None,
                   help="also write the full result (ladder + elastic/"
                        "cohort metadata + observability snapshot) to "
                        "this path, e.g. MULTICHIP_r06.json")
    p.add_argument("--body", action="store_true",
                   help=argparse.SUPPRESS)
    return p.parse_args(argv)


def _run_body(args) -> int:
    import jax
    from mxnet_tpu import elastic, observability

    n = len(jax.devices())
    if args.sizes:
        sizes = [int(s) for s in args.sizes.split(",") if s]
        missing = [s for s in sizes if s > n]
        if missing:
            # an explicitly-requested size the hardware can't provide
            # must fail LOUDLY: silently clamping would let the 8->64
            # gate "pass" with base==max (a vacuous efficiency of 1.0)
            _emit(_diagnostic(
                "insufficient_devices",
                f"requested mesh sizes {missing} exceed the {n} visible "
                f"devices — refusing to fake the scaling ladder"))
            return 1
    else:
        sizes = [s for s in (1, 2, 4, 8, 16, 32, 64) if s <= n]
    results = {}
    for s in sizes:
        per_chip = measure(s, args.batch_per_chip, args.steps,
                           args.warmup, args.network, args.classes,
                           args.image, args.bf16)
        results[s] = per_chip
        print(json.dumps({"chips": s,
                          "images_per_sec_per_chip": round(per_chip, 2)}),
              file=sys.stderr, flush=True)
    base = results[sizes[0]]
    obs = observability.snapshot()
    _emit({
        "metric": METRIC,
        "value": round(results[sizes[-1]] / base, 4),
        "target": 0.9,
        "base_chips": sizes[0], "max_chips": sizes[-1],
        "network": args.network, "bf16": bool(args.bf16),
        "batch_per_chip": args.batch_per_chip,
        "platform": jax.devices()[0].platform,
        "ladder": {str(s): round(v, 2) for s, v in results.items()},
        # cohort/elastic provenance (docs/elastic.md): world shape +
        # env wiring, so a pod-slice artifact records which cohort ran
        "elastic": elastic.elastic_metadata(),
        "observability": obs,
    })
    return 0


def main() -> int:
    args = _parse_args()
    if args.body:
        return _run_body(args)

    from mxnet_tpu.diagnostics import get_journal, guard
    j = get_journal()

    def _killed():
        doc = _diagnostic(
            "scaling_killed",
            f"killed at phase {j.last_phase!r} before completion (outer "
            "deadline or signal); see stderr journal for breadcrumbs")
        _emit(doc)
        _write_artifact(args.artifact, doc)

    j.install_handlers(final_cb=_killed)
    with j.phase("scaling_probe"):
        try:
            info = guard.probe_backend()
        except guard.DeviceUnreachable as e:
            doc = _diagnostic("device_unreachable", e.to_dict().get(
                "detail", str(e)))
            _emit(doc)
            _write_artifact(args.artifact, doc)
            j.mark_clean()
            return 0
    body_deadline = (BODY_TIMEOUT_S if info["platform"] in ("tpu", "axon")
                     else BODY_TIMEOUT_CPU_S)
    j.set_phase("scaling_body")
    child_args, skip = [], False
    for a in sys.argv[1:]:
        if skip:
            skip = False
        elif a == "--artifact":
            skip = True            # the parent owns artifact writing
        elif not a.startswith("--artifact="):
            child_args.append(a)
    child_cmd = [sys.executable, os.path.abspath(__file__),
                 "--body"] + child_args
    try:
        proc = subprocess.run(child_cmd, capture_output=True, text=True,
                              timeout=body_deadline)
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"").decode("utf-8", "replace")
                if isinstance(e.stderr, bytes) else (e.stderr or ""))[-500:]
        doc = _diagnostic(
            "scaling_timeout",
            f"probe was healthy ({info['n']}x {info['platform']}) but the "
            f"body exceeded {body_deadline:g}s; stderr tail: {tail}")
        _emit(doc)
        _write_artifact(args.artifact, doc)
        j.mark_clean()
        return 0
    j.set_phase("scaling_report")
    sys.stderr.write(proc.stderr[-3000:])
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if not isinstance(parsed, dict) or parsed.get("metric") != METRIC:
            continue
        print(line, flush=True)
        _write_artifact(args.artifact, parsed)
        j.mark_clean()
        return 0 if proc.returncode == 0 else proc.returncode
    doc = _diagnostic(
        "scaling_body_failed",
        f"rc={proc.returncode}; no parseable metric line on stdout; "
        f"stderr tail: {proc.stderr[-500:]}")
    _emit(doc)
    _write_artifact(args.artifact, doc)
    j.mark_clean()
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Hardware parity sweep: run the §4 consistency check (the reference's
check_consistency / test_operator_gpu.py pattern — CPU is the oracle for
the accelerator) against the REAL chip.

For each op in the sweep: compute on the TPU via the normal dispatch
path, recompute the same op with numpy/CPU math, and compare at
dtype-appropriate tolerance. Covers the compute core the models lean on:
conv/dense/norms/softmax/attention/reductions + a fused train step.

Usage: PYTHONPATH=.:/root/.axon_site python benchmarks/hw_parity.py
Prints PASS/FAIL per op and a summary line.
"""
from __future__ import annotations

import numpy as np


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    platform = jax.devices()[0].platform
    print(f"platform={platform}")
    rng = np.random.RandomState(0)
    results = []

    def check(name, got, want, rtol=2e-2, atol=2e-3):
        got = np.asarray(got)
        want = np.asarray(want)
        ok = np.allclose(got, want, rtol=rtol, atol=atol)
        err = float(np.max(np.abs(got - want) /
                           (np.abs(want) + atol))) if got.size else 0.0
        results.append((name, ok, err))
        print(f"{'PASS' if ok else 'FAIL'} {name:<28} max rel err "
              f"{err:.2e}", flush=True)

    # dense / conv / norm cores
    x = rng.randn(32, 64).astype(np.float32)
    w = rng.randn(128, 64).astype(np.float32)
    b = rng.randn(128).astype(np.float32)
    check("FullyConnected",
          mx.nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                               num_hidden=128).asnumpy(),
          x @ w.T + b, rtol=1e-3, atol=1e-4)

    xc = rng.randn(4, 8, 16, 16).astype(np.float32)
    wc = rng.randn(12, 8, 3, 3).astype(np.float32)
    got = mx.nd.Convolution(nd.array(xc), nd.array(wc),
                            kernel=(3, 3), num_filter=12,
                            no_bias=True).asnumpy()
    # NUMPY oracle (a lax conv would run on the same device under the
    # same precision config — tautological): sliding windows + einsum
    win = np.lib.stride_tricks.sliding_window_view(
        xc, (3, 3), axis=(2, 3))             # (N, C, OH, OW, 3, 3)
    want = np.einsum("nchwij,ocij->nohw", win, wc)
    check("Convolution3x3", got, want, rtol=1e-3, atol=1e-4)

    xb = (rng.randn(16, 8, 6, 6) * 3 + 5).astype(np.float32)
    g1 = np.abs(rng.randn(8).astype(np.float32)) + 0.5
    b1 = rng.randn(8).astype(np.float32)
    with autograd.record(train_mode=True):
        out, bm, bv = mx.nd.BatchNorm(
            nd.array(xb), nd.array(g1), nd.array(b1),
            nd.array(np.zeros(8, np.float32)),
            nd.array(np.zeros(8, np.float32)),
            fix_gamma=False, output_mean_var=True)
    mu = xb.mean(axis=(0, 2, 3), keepdims=True)
    var = xb.var(axis=(0, 2, 3), keepdims=True)
    want = (xb - mu) / np.sqrt(var + 1e-3) * g1.reshape(1, -1, 1, 1) \
        + b1.reshape(1, -1, 1, 1)
    check("BatchNorm(train)", out.asnumpy(), want, rtol=1e-2, atol=1e-3)

    xl = rng.randn(8, 32).astype(np.float32)
    gl = np.ones(32, np.float32)
    bl = np.zeros(32, np.float32)
    mu = xl.mean(-1, keepdims=True)
    sd = np.sqrt(xl.var(-1, keepdims=True) + 1e-5)
    check("LayerNorm",
          mx.nd.LayerNorm(nd.array(xl), nd.array(gl),
                          nd.array(bl)).asnumpy(),
          (xl - mu) / sd, rtol=1e-3, atol=1e-4)

    s = rng.randn(6, 40).astype(np.float32) * 4
    e = np.exp(s - s.max(-1, keepdims=True))
    check("softmax", mx.nd.softmax(nd.array(s)).asnumpy(),
          e / e.sum(-1, keepdims=True), rtol=1e-3, atol=1e-5)
    check("logsumexp",
          mx.nd.logsumexp(nd.array(s), axis=-1).asnumpy(),
          np.log(np.exp(s - s.max(-1, keepdims=True))
                 .sum(-1)) + s.max(-1), rtol=1e-4, atol=1e-4)

    # fused attention vs dense oracle
    B, S, H, D = 2, 64, 4, 16
    qkv = rng.randn(B, S, 3 * H * D).astype(np.float32) * 0.3
    got = mx.nd.contrib.fused_self_attention(
        nd.array(qkv), heads=H, causal=True).asnumpy()
    q = qkv[:, :, :H * D].reshape(B, S, H, D)
    k = qkv[:, :, H * D:2 * H * D].reshape(B, S, H, D)
    v = qkv[:, :, 2 * H * D:].reshape(B, S, H, D)
    sc = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    mask = np.triu(np.full((S, S), -1e30), 1)
    sc = sc + mask
    p = np.exp(sc - sc.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, S, H * D)
    check("fused_self_attention", got, want, rtol=1e-2, atol=1e-3)

    # one fused train step: loss must match a CPU-computed reference
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    xs = rng.randn(8, 10).astype(np.float32)
    ys = rng.randint(0, 4, (8,))
    with autograd.record():
        outp = net(nd.array(xs))
        loss = gluon.loss.SoftmaxCrossEntropyLoss()(outp,
                                                    nd.array(ys))
    w1 = net[0].weight.data().asnumpy()
    b1_ = net[0].bias.data().asnumpy()
    w2 = net[1].weight.data().asnumpy()
    b2_ = net[1].bias.data().asnumpy()
    h = np.maximum(xs @ w1.T + b1_, 0)
    logits = h @ w2.T + b2_
    lse = np.log(np.exp(logits - logits.max(-1, keepdims=True))
                 .sum(-1)) + logits.max(-1)
    want_loss = lse - logits[np.arange(8), ys]
    check("train-step loss", loss.asnumpy(), want_loss,
          rtol=1e-3, atol=1e-4)

    # backward parity: autograd gradients vs hand-derived numpy math
    # (the reference's GPU tier checks both directions — SURVEY §4)
    xg = nd.array(x)
    wg = nd.array(w)
    xg.attach_grad()
    wg.attach_grad()
    ct = rng.randn(32, 128).astype(np.float32)
    with autograd.record():
        o = mx.nd.FullyConnected(xg, wg, nd.array(b), num_hidden=128)
        lo = (o * nd.array(ct)).sum()
    lo.backward()
    check("FC dL/dx", xg.grad.asnumpy(), ct @ w, rtol=1e-3, atol=1e-4)
    check("FC dL/dw", wg.grad.asnumpy(), ct.T @ x, rtol=1e-3, atol=1e-4)

    xcg = nd.array(xc)
    xcg.attach_grad()
    ctc = rng.randn(4, 12, 14, 14).astype(np.float32)
    with autograd.record():
        oc = mx.nd.Convolution(xcg, nd.array(wc), kernel=(3, 3),
                               num_filter=12, no_bias=True)
        lc = (oc * nd.array(ctc)).sum()
    lc.backward()
    # numpy dL/dx: full-correlation of cotangent with flipped kernels
    pad_ct = np.zeros((4, 12, 18, 18), np.float32)
    pad_ct[:, :, 2:16, 2:16] = ctc
    wflip = wc[:, :, ::-1, ::-1]
    win_ct = np.lib.stride_tricks.sliding_window_view(
        pad_ct, (3, 3), axis=(2, 3))
    want_dx = np.einsum("nohwij,ocij->nchw", win_ct, wflip)
    check("conv dL/dx", xcg.grad.asnumpy(), want_dx,
          rtol=1e-3, atol=1e-4)

    n_fail = sum(not ok for _, ok, _ in results)
    print(f"hw_parity: {len(results) - n_fail}/{len(results)} ops match "
          f"the CPU oracle on {platform}")
    return 1 if n_fail else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())

#!/usr/bin/env python
"""Pallas conv-epilogue probe (VERDICT r4 Weak #8 / Next #7).

Round-4 analysis pinned RN50 at 2686 img/s vs a 3550 HBM ceiling and
attributed the residual ~24% to XLA's conv-fusion bandwidth efficiency
(625/819 GB/s), declaring it "not framework-reachable". This probe tests
the one named candidate lever: fusing the BN-scale + residual-add + relu
epilogue of a stage-3/4 bottleneck conv into a hand Pallas kernel, vs
letting XLA fuse the same ops into its conv consumer.

Two timed variants on the stage-3 3x3 shape (N=64, 14x14, C=256, bf16):
  xla     conv -> scale*x+bias -> +res -> relu, one jit (XLA fuses)
  pallas  conv under jit, epilogue as ONE Pallas VMEM pass

If the Pallas variant wins, part of the 24% is reclaimable and the next
step is widening the epilogue; if it loses or ties, the round-4 claim
gains evidence (the epilogue is already fused; the gap lives inside the
conv itself). Either outcome goes to docs/perf_notes.md.

CPU: runs a tiny interpret-mode correctness check only (no timing claim).
Prints one JSON line per variant.
"""
from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _block(n, cap):
    """Largest divisor of n that is <= cap (grid must tile n exactly —
    a floor-divided grid would leave the remainder rows unwritten)."""
    for b in range(min(cap, n), 0, -1):
        if n % b == 0:
            return b
    return 1


def epilogue_pallas(y, scale, bias, res, interpret=False):
    """relu(y * scale + bias + res) in one VMEM pass over (R, C) rows."""
    from jax.experimental import pallas as pl

    r, c = y.shape
    br = _block(r, 512)
    bc = _block(c, 256)

    def kernel(y_ref, s_ref, b_ref, res_ref, o_ref):
        x = y_ref[...].astype(jnp.float32)
        out = x * s_ref[...] + b_ref[...] + res_ref[...].astype(jnp.float32)
        o_ref[...] = jnp.maximum(out, 0.0).astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=(r // br, c // bc),
        in_specs=[pl.BlockSpec((br, bc), lambda i, j: (i, j)),
                  pl.BlockSpec((1, bc), lambda i, j: (0, j)),
                  pl.BlockSpec((1, bc), lambda i, j: (0, j)),
                  pl.BlockSpec((br, bc), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((br, bc), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((r, c), y.dtype),
        interpret=interpret,
    )(y, scale, bias, res)


def main():
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    if on_tpu:
        n, h, w, cin, cout = 64, 14, 14, 256, 256
        steps, reps = 30, 3
    else:
        n, h, w, cin, cout = 2, 14, 14, 128, 128
        steps, reps = 2, 1

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    x = jnp.asarray(rng.randn(n, h, w, cin), dtype=dt)
    k = jnp.asarray(rng.randn(3, 3, cin, cout) * 0.05, dtype=dt)
    scale = jnp.asarray(rng.rand(1, cout) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(1, cout) * 0.1, jnp.float32)
    res = jnp.asarray(rng.randn(n, h, w, cout), dtype=dt)

    conv = functools.partial(
        jax.lax.conv_general_dilated, window_strides=(1, 1),
        padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)

    @jax.jit
    def step_xla(x, k, scale, bias, res):
        y = conv(x, k)
        y = y * scale.reshape(1, 1, 1, -1) + bias.reshape(1, 1, 1, -1)
        return jnp.maximum(y + res.astype(jnp.float32), 0.0).astype(x.dtype)

    @jax.jit
    def step_pallas(x, k, scale, bias, res):
        y = conv(x, k).astype(x.dtype)
        flat = y.reshape(-1, y.shape[-1])
        out = epilogue_pallas(flat, scale, bias,
                              res.reshape(-1, res.shape[-1]),
                              interpret=not on_tpu)
        return out.reshape(y.shape)

    # correctness first (fp32 reference)
    a = np.asarray(step_xla(x, k, scale, bias, res), np.float32)
    b = np.asarray(step_pallas(x, k, scale, bias, res), np.float32)
    err = float(np.abs(a - b).max())
    tol = 0.1 if on_tpu else 1e-3        # bf16 conv accumulate reorder
    if err > tol:
        print(json.dumps({"metric": "conv_epilogue_probe",
                          "error": "mismatch", "max_err": err}))
        return 1

    results = {}
    for name, fn in [("xla", step_xla), ("pallas", step_pallas)]:
        fn(x, k, scale, bias, res).block_until_ready()
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(steps):
                out = fn(x, k, scale, bias, res)
            out.block_until_ready()
            dtm = (time.perf_counter() - t0) / steps
            best = dtm if best is None else min(best, dtm)
        results[name] = best
        # ms + ratio only: a GB/s figure from whole-step time would
        # attribute conv time to the epilogue and mislead perf_notes
        print(json.dumps({
            "metric": f"conv_epilogue_{name}_ms", "value": round(best * 1e3, 3),
            "unit": f"ms/step ({platform}, {n}x{h}x{w}x{cin}->{cout})",
        }))
    print(json.dumps({
        "metric": "conv_epilogue_pallas_speedup",
        "value": round(results["xla"] / results["pallas"], 4),
        "unit": "x (xla_ms / pallas_ms; >1 means pallas wins)",
        "max_err": err,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""BERT-base pretraining throughput (BASELINE.md metric of record #2:
samples/sec/chip at seq 128; derived 50%-MFU ceiling ≈ 1.2k/chip on v5e).

Same methodology as bench.py: fused multi-step dispatch + best of three
hard-synced windows. Prints one JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import bert

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    batch = 128 if on_tpu else 4
    seq = 128 if on_tpu else 32
    vocab = 30522 if on_tpu else 512
    k = 8 if on_tpu else 2
    steps = 4 if on_tpu else 1
    windows = 3 if on_tpu else 1

    if on_tpu:
        net = bert.get_bert_model(
            "bert_12_768_12", vocab_size=vocab, max_length=512,
            dropout=0.1, use_pooler=False, use_classifier=False)
    else:            # tiny config for the CPU smoke path
        net = bert.BERTModel(num_layers=2, units=64, hidden_size=128,
                             num_heads=4, max_length=128, vocab_size=vocab,
                             use_pooler=False, use_classifier=False)
    net.initialize(mx.init.Normal(0.02))

    class MLMWrapper(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, tokens):
            # keep the logits 3-D (B, S, V): the CE loss picks/reduces over
            # the last axis in place — flattening to (B*S, V) forced XLA to
            # relayout the 1 GB logits tensor (copy.1217, 2 GB of HBM
            # traffic, docs/perf_notes.md round 4)
            _, mlm = self.inner(tokens)
            return mlm

    # bf16 master weights + adam moments: adam state is 3×fp32 tensors of
    # param size — on a 110 M-param model that is ~2.6 GB/step of optimizer
    # traffic, +10.5% measured when halved (perf_notes round 4); conver-
    # gence-gated against fp32 masters in tests/test_convergence.py
    mesh = parallel.make_mesh({"data": len(jax.devices())})
    trainer = parallel.ShardedTrainer(
        MLMWrapper(net), gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-4},
        mesh=mesh, compute_dtype="bfloat16" if on_tpu else None,
        master_dtype="bfloat16" if on_tpu else None)

    toks = np.random.randint(0, vocab, (batch, seq))
    trainer.run_steps(toks, toks, num_steps=k).wait_to_read()
    best = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = trainer.run_steps(toks, toks, num_steps=k)
        np.asarray(loss.asnumpy())
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    n_chips = len(jax.devices())
    sps = batch * steps * k / best / n_chips
    print(json.dumps({
        "metric": "bert_base_train_samples_per_sec_per_chip",
        "value": round(sps, 2),
        "unit": f"samples/sec/chip ({platform}, batch={batch}, seq={seq})",
        "vs_baseline": round(sps / 1200.0, 4),
    }))


if __name__ == "__main__":
    main()

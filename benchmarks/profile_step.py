#!/usr/bin/env python
"""Capture a hardware profile of a train step and print an HBM traffic
budget per HLO op class (round-4 verdict #2: "HLO-level traffic table").

Captures an xplane trace of k scanned train steps with jax.profiler,
then converts it with xprof's raw_to_tool_data (the same machinery the
tensorboard profile plugin uses) into hlo_stats, and aggregates
time and bytes-accessed per op category.

Usage:
  PYTHONPATH=.:/root/.axon_site python benchmarks/profile_step.py rn50
  PYTHONPATH=.:/root/.axon_site python benchmarks/profile_step.py bert \
      [--master-dtype bfloat16]

Prints: per-category table (self time ms, GB accessed per step, % of
step) + the top 15 individual HLO fusions by bytes.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_rn50(master_dtype):
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    net = resnet50_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    mesh = parallel.make_mesh({"data": len(jax.devices())})
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9},
        mesh=mesh, compute_dtype="bfloat16",
        master_dtype=master_dtype)
    x = np.random.uniform(-1, 1, (256, 3, 224, 224)).astype(np.float32)
    y = np.random.randint(0, 1000, (256,))
    return trainer, (x, y)


def build_bert(master_dtype):
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import bert

    vocab = 30522
    net = bert.get_bert_model(
        "bert_12_768_12", vocab_size=vocab, max_length=512,
        dropout=0.1, use_pooler=False, use_classifier=False)
    net.initialize(mx.init.Normal(0.02))

    class MLMWrapper(gluon.HybridBlock):
        # 3-D logits, same as benchmarks/bert.py's shipped config (the
        # flat reshape forced a 2 GB logits relayout — perf_notes round 4)
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, tokens):
            _, mlm = self.inner(tokens)
            return mlm

    mesh = parallel.make_mesh({"data": len(jax.devices())})
    trainer = parallel.ShardedTrainer(
        MLMWrapper(net), gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-4},
        mesh=mesh, compute_dtype="bfloat16", master_dtype=master_dtype)
    toks = np.random.randint(0, 30000, (128, 128))
    return trainer, (toks, toks)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", choices=["rn50", "bert"])
    ap.add_argument("--master-dtype", default=None)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--keep-trace", default=None,
                    help="directory to keep the raw trace in")
    args = ap.parse_args()

    import jax

    trainer, batch = (build_rn50 if args.model == "rn50"
                      else build_bert)(args.master_dtype)
    k = args.steps
    # warm up / compile outside the trace
    np.asarray(trainer.run_steps(*batch, num_steps=k).asnumpy())

    tracedir = args.keep_trace or tempfile.mkdtemp(prefix="mxtpu_trace_")
    with jax.profiler.trace(tracedir):
        np.asarray(trainer.run_steps(*batch, num_steps=k).asnumpy())

    # same xprof hlo_stats pipeline mx.profiler.device_stats uses
    from mxnet_tpu.profiler import _parse_hlo_stats
    rows = _parse_hlo_stats(tracedir)

    def field(row, label, default=0.0):
        v = row.get(label)
        if v in (None, ""):
            return default
        try:
            return float(v)
        except (TypeError, ValueError):
            return v

    total_time = 0.0
    cats = {}
    tops = []
    for r in rows:
        name = field(r, "HLO op name", "")
        cat = field(r, "HLO op category", "") or "uncategorized"
        t = field(r, "Total self time (us)")
        occ = field(r, "#Occurrences", 1.0)
        hbm_bw = field(r, "HBM BW (GiB/s)")       # GiB/s of self time
        mem_bw = field(r, "Measured memory BW (GiB/s)")
        bound = field(r, "Bound by", "")
        hbm_gb = hbm_bw * (t / 1e6) * 1.073741824
        c = cats.setdefault(cat, [0.0, 0.0, 0.0])
        c[0] += t
        c[1] += hbm_gb
        c[2] += occ
        total_time += t
        tops.append((t, hbm_gb, name, cat, bound, mem_bw))

    per_step = k
    print(f"model={args.model} master_dtype={args.master_dtype} "
          f"steps_traced={k}")
    print(f"{'category':<28} {'ms/step':>9} {'HBM GB/step':>12} "
          f"{'%time':>6} {'#ops':>6}")
    for label, (t, g, n) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        print(f"{label:<28} {t / 1e3 / per_step:9.3f} "
              f"{g / per_step:12.2f} {100 * t / total_time:6.1f} "
              f"{int(n / per_step):>6}")
    print(f"{'TOTAL':<28} {total_time / 1e3 / per_step:9.3f} "
          f"{sum(c[1] for c in cats.values()) / per_step:12.2f}")
    print("\ntop HLO ops by self time:")
    for t, g, name, label, bound, mem_bw in sorted(tops, reverse=True)[:20]:
        print(f"  {t / 1e3 / per_step:7.3f} ms/step {g / per_step:7.2f} "
              f"HBM-GB  bound:{str(bound):<11} {label:<22} {name[:58]}")
    if not args.keep_trace:
        import shutil
        shutil.rmtree(tracedir, ignore_errors=True)


if __name__ == "__main__":
    main()

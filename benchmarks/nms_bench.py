"""box_nms micro-benchmark: fixed-point matrix NMS (shipped) vs the
round-1 sequential fori_loop formulation, at SSD-like sizes.

Run: PYTHONPATH=. python benchmarks/nms_bench.py [--n 400]
"""
from __future__ import annotations

import argparse
import time

import numpy as np


def _sequential_nms_one(rows, overlap_thresh, k):
    """The round-1 formulation: O(topk) serial fori_loop (baseline)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mxnet_tpu.ops.contrib import _box_iou_corner
    scores = rows[:, 1]
    boxes = rows[:, 2:6]
    valid = scores > 0.0
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
    n = rows.shape[0]
    iou = _box_iou_corner(boxes[order], boxes[order])
    valid_sorted = valid[order]

    def body(i, keep):
        sup = (iou[i] > overlap_thresh) & keep[i] & (jnp.arange(n) > i)
        return jnp.where(sup, False, keep)

    keep = lax.fori_loop(0, k, body, valid_sorted)
    keep &= jnp.arange(n) < k
    perm = jnp.argsort(~keep, stable=True)
    return jnp.where(jnp.sort(~keep, stable=True)[:, None],
                     -jnp.ones_like(rows), rows[order][perm])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=400)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp


    rng = np.random.RandomState(0)
    n, b = args.n, args.batch
    ctr = rng.rand(b, n, 2) * 100
    wh = rng.rand(b, n, 2) * 20 + 1
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], -1)
    ids = rng.randint(0, 20, (b, n, 1)).astype(np.float32)
    scores = rng.rand(b, n, 1).astype(np.float32)
    data = np.concatenate([ids, scores, boxes.astype(np.float32)], -1)

    from jax import lax

    def scan_time(core, k1=4, k2=64):
        """Per-call device time with the dispatch round-trip differenced
        out (same methodology as perf_probe.py)."""
        def make(k):
            def run(d):
                def body(c, _):
                    out = core(d + (c * 1e-30).astype(d.dtype))
                    return jnp.sum(out[..., 0]).astype(jnp.float32), None
                c, _ = lax.scan(body, jnp.zeros(()), None, length=k)
                return c
            return jax.jit(run)
        f1, f2 = make(k1), make(k2)
        xd = jnp.asarray(data)
        np.asarray(f1(xd)), np.asarray(f2(xd))

        def tmin(f, it=4):
            best = None
            for _ in range(it):
                t0 = time.perf_counter()
                np.asarray(f(xd))
                dt = time.perf_counter() - t0
                best = dt if best is None or dt < best else best
            return best
        return (tmin(f2) - tmin(f1)) / (k2 - k1)

    from mxnet_tpu.ops.contrib import _box_nms
    t_new = scan_time(lambda d: _box_nms(
        d, overlap_thresh=0.5, topk=n, coord_start=2, score_index=1,
        id_index=0, force_suppress=True))
    t_old = scan_time(jax.vmap(lambda r: _sequential_nms_one(r, 0.5, n)))

    print(f"n={n} batch={b}: sequential {t_old*1e3:8.2f} ms | "
          f"fixed-point {t_new*1e3:8.2f} ms | speedup "
          f"{t_old/t_new:5.1f}x")


if __name__ == "__main__":
    main()

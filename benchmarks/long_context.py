#!/usr/bin/env python
"""Long-context attention throughput ladder (SURVEY §5.7 — the net-new
TPU capability: blockwise/Pallas flash attention for sequences far past
the reference's ~512-token BucketingModule ceiling).

Measures one BERT-style self-attention layer (fused QKV projection +
``_contrib_fused_self_attention`` + output projection) forward+backward
across a sequence ladder on the available device. Short sequences route
to the fused dense path; S > 1024 engages the streaming flash kernel
(Pallas on TPU hardware, blockwise jnp elsewhere), whose memory is O(S)
instead of O(S²) — the dense scores tensor for S=32k at batch 1/head 12
would alone be 12·32768² fp32 ≈ 48 GB, past HBM.

Methodology: bench.py's staged-batch, k-step-scan, best-of-3-windows
timing (see docs/perf_notes.md "Measurement pitfalls").

Usage: PYTHONPATH=.:/root/.axon_site python benchmarks/long_context.py
       [--seqs 512 2048 8192 16384 32768] [--units 768] [--heads 12]
"""
from __future__ import annotations

import argparse
import time

import numpy as np


def measure(seq, units, heads, on_tpu):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.contrib import _fused_self_attention

    tokens = 16384 if on_tpu else 2048      # constant work per config
    batch = max(1, tokens // seq)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, seq, units) * 0.02, dtype)
    w_qkv = jnp.asarray(rng.randn(units, 3 * units) * 0.02, dtype)
    w_out = jnp.asarray(rng.randn(units, units) * 0.02, dtype)

    def layer(x, w_qkv, w_out):
        qkv = x @ w_qkv                      # the full QKV projection
        out = _fused_self_attention(qkv, heads=heads, causal=True,
                                    block_size=1024)
        out = out @ w_out
        return jnp.sum(out.astype(jnp.float32) ** 2)

    grad = jax.grad(layer, argnums=(0, 1, 2))

    k = 8 if on_tpu else 2

    @jax.jit
    def steps(x, w_qkv, w_out):
        def body(c, _):
            g_x, g_qkv, g_out = grad(c, w_qkv, w_out)
            return c - 1e-6 * g_x.astype(c.dtype), jnp.sum(
                g_qkv.astype(jnp.float32)) + jnp.sum(
                g_out.astype(jnp.float32))
        c, s = jax.lax.scan(body, x, jnp.arange(k))
        return s[-1]

    np.asarray(steps(x, w_qkv, w_out))      # compile + warm
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(4 if on_tpu else 1):
            s = steps(x, w_qkv, w_out)
        np.asarray(s)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    n_disp = 4 if on_tpu else 1
    tok_s = batch * seq * n_disp * k / best
    print(f"S={seq:<6} batch={batch:<3} {best / (n_disp * k) * 1e3:9.2f} "
          f"ms/step {tok_s:12.0f} tokens/s fwd+bwd", flush=True)
    return tok_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", type=int, nargs="+", default=None)
    ap.add_argument("--units", type=int, default=None)
    ap.add_argument("--heads", type=int, default=None)
    args = ap.parse_args()
    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    # CPU smoke crosses the s > 1024 threshold too, so the streaming
    # blockwise path (the point of this benchmark) is exercised off-TPU
    seqs = args.seqs or ([512, 2048, 8192, 16384, 32768] if on_tpu
                         else [256, 2048])
    units = args.units or (768 if on_tpu else 64)
    heads = args.heads or (12 if on_tpu else 4)
    print(f"platform={jax.devices()[0].platform} units={units} "
          f"heads={heads} (constant tokens/config; causal)", flush=True)
    for s in seqs:
        measure(s, units, heads, on_tpu)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Throughput of the non-headline driver configs on the real chip —
BASELINE.md asks for these to be recorded once the models run:
  nmt        Sockeye-geometry transformer (6L/512/2048/8h), seq 64,
             teacher-forced train step, tokens/sec
  ssd        SSD-512-style resnet18 detector train step, images/sec
  bert_large bert_24_1024_16 MLM train step (batch sized to fit HBM),
             samples/sec

Same staged-batch k-step methodology as bench.py. Prints one JSON line
per model.

Usage: PYTHONPATH=.:/root/.axon_site python \
           benchmarks/model_zoo_throughput.py [nmt ssd bert_large]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _measure(trainer, batch, per_step, unit, name, k, dispatches=4,
             windows=3):
    # stage the batch on device once (bench.py's staged-batch protocol —
    # steady-state steps must not pay the tunnel's ~6 MB/s host->device
    # link; a production input pipeline double-buffers these transfers)
    trainer._prepare(batch[:-1])
    batch = tuple(trainer._shard_batch_arg(b) for b in batch)
    np.asarray(trainer.run_steps(*batch, num_steps=k).asnumpy())
    best = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(dispatches):
            loss = trainer.run_steps(*batch, num_steps=k)
        np.asarray(loss.asnumpy())
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    import jax
    rate = per_step * dispatches * k / best / len(jax.devices())
    print(json.dumps({"metric": name, "value": round(rate, 1),
                      "unit": unit,
                      "ms_per_step": round(best / dispatches / k * 1e3,
                                           2)}))


def bench_nmt(on_tpu):
    import jax
    from mxnet_tpu import gluon, parallel
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import transformer

    vocab = 32000 if on_tpu else 128
    batch, seq = (64, 64) if on_tpu else (2, 8)
    net = transformer.TransformerModel(
        src_vocab=vocab, tgt_vocab=vocab,
        num_layers=6 if on_tpu else 1, units=512 if on_tpu else 32,
        hidden_size=2048 if on_tpu else 64,
        num_heads=8 if on_tpu else 2, dropout=0.1,
        max_length=max(512, seq))
    net.initialize(mx.init.Xavier())

    class Seq2SeqWrapper(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, src, tgt):
            return self.inner(src, tgt)       # (B, T, V) logits

    mesh = parallel.make_mesh({"data": len(jax.devices())})
    trainer = parallel.ShardedTrainer(
        Seq2SeqWrapper(net),
        gluon.loss.SoftmaxCrossEntropyLoss(label_smoothing=0.1),
        "adam", {"learning_rate": 1e-4},
        mesh=mesh, compute_dtype="bfloat16" if on_tpu else None,
        master_dtype="bfloat16" if on_tpu else None)
    rng = np.random.RandomState(0)
    src = rng.randint(1, vocab, (batch, seq))
    tgt = rng.randint(1, vocab, (batch, seq))
    _measure(trainer, (src, tgt, tgt), batch * seq,
             f"target tokens/sec/chip (batch={batch}, seq={seq})",
             "nmt_transformer_train_tokens_per_sec", k=8 if on_tpu else 2)


def bench_ssd(on_tpu):
    import jax
    from mxnet_tpu import gluon, parallel
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import ssd as ssd_zoo

    batch = 32 if on_tpu else 2
    shape = 512 if on_tpu else 64
    classes = 20
    # the NAMED zoo config: ssd_512_resnet18_v1 is 5-scale
    net = ssd_zoo.get_ssd("resnet18_v1", classes=classes,
                          num_scales=5 if on_tpu else 3,
                          thumbnail=not on_tpu)
    net.initialize(mx.init.Xavier())
    loss_fn = ssd_zoo.SSDMultiBoxLoss()

    class SSDTrainBlock(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, x, labels):
            anchors, cls_preds, box_preds = self.inner(x)
            loc_t, loc_m, cls_t = F.contrib.MultiBoxTarget(
                anchors, labels, cls_preds, negative_mining_ratio=3.0)
            return F.stack(*loss_fn(cls_preds, box_preds, cls_t, loc_t,
                                    loc_m), axis=0)

    class PassThrough(gluon.loss.Loss):
        amp_safe = True

        def __init__(self):
            super().__init__(None, 0)

        def hybrid_forward(self, F, pred, label):
            return F.sum(pred)

    mesh = parallel.make_mesh({"data": len(jax.devices())})
    trainer = parallel.ShardedTrainer(
        SSDTrainBlock(net), PassThrough(), "sgd",
        {"learning_rate": 5e-3, "momentum": 0.9, "wd": 5e-4},
        mesh=mesh, compute_dtype="bfloat16" if on_tpu else None,
        master_dtype="bfloat16" if on_tpu else None)
    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, shape, shape).astype(np.float32)
    labels = np.full((batch, 4, 5), -1.0, np.float32)
    labels[:, 0] = [0, 0.2, 0.2, 0.6, 0.7]
    _measure(trainer, (x, labels, labels), batch,
             f"images/sec/chip (batch={batch}, {shape}x{shape})",
             "ssd512_resnet18_train_images_per_sec", k=8 if on_tpu else 2)


def bench_bert_large(on_tpu):
    import jax
    from mxnet_tpu import gluon, parallel
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import bert

    vocab = 30522 if on_tpu else 256
    batch, seq = (32, 128) if on_tpu else (2, 16)
    if on_tpu:
        net = bert.get_bert_model("bert_24_1024_16", vocab_size=vocab,
                                  max_length=512, dropout=0.1,
                                  use_pooler=False, use_classifier=False)
    else:
        net = bert.BERTModel(num_layers=2, units=64, hidden_size=128,
                             num_heads=4, max_length=128,
                             vocab_size=vocab, use_pooler=False,
                             use_classifier=False)
    net.initialize(mx.init.Normal(0.02))

    class MLMWrapper(gluon.HybridBlock):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def hybrid_forward(self, F, tokens):
            _, mlm = self.inner(tokens)
            return mlm

    mesh = parallel.make_mesh({"data": len(jax.devices())})
    trainer = parallel.ShardedTrainer(
        MLMWrapper(net), gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 1e-4},
        mesh=mesh, compute_dtype="bfloat16" if on_tpu else None,
        master_dtype="bfloat16" if on_tpu else None)
    toks = np.random.RandomState(0).randint(0, vocab, (batch, seq))
    _measure(trainer, (toks, toks), batch,
             f"samples/sec/chip (batch={batch}, seq={seq})",
             "bert_large_train_samples_per_sec", k=8 if on_tpu else 2)


def main():
    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    which = sys.argv[1:] or ["nmt", "ssd", "bert_large"]
    for name in which:
        {"nmt": bench_nmt, "ssd": bench_ssd,
         "bert_large": bench_bert_large}[name](on_tpu)


if __name__ == "__main__":
    main()

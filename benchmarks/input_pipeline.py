"""Real-input-pipeline ResNet-50 training throughput.

The headline bench (bench.py) stages one synthetic batch on-device; this
variant feeds the SAME fused train step from an actual RecordIO pack
through ImageRecordIter / a raw-record reader + PrefetchingIter —
measuring the trainable end-to-end rate (SURVEY §2 #34's double-buffered
host→device pipeline, ref: src/io/iter_image_recordio_2.cc +
iter_prefetcher.h).

Two pack formats:
  --format jpeg  JPEG-encoded records (the reference's ImageRecordIO):
                 decode+augment dominates on weak hosts.
  --format raw   uint8 CHW tensors in the records; normalization runs ON
                 DEVICE as the first op of the compiled step (cast+scale
                 fused into the first conv) — the TPU-idiomatic split:
                 the host only reads, batches, and ships bytes.

Prints per-variant images/sec/chip next to the synthetic-batch number so
the input-pipeline overhead is explicit. On this 1-core tunnel VM the
jpeg variant is decode-bound by design — the number demonstrates overlap,
not the TPU's ceiling.
"""
from __future__ import annotations

import argparse
import os
import time

import numpy as np


def make_packs(tmpdir, n, shape_hw, fmt):
    """Generate a labeled pack of random images (once, cached)."""
    import mxnet_tpu as mx
    from mxnet_tpu import recordio
    h, w = shape_hw
    path = os.path.join(tmpdir, f"bench_{fmt}_{n}_{h}.rec")
    idxp = path.replace(".rec", ".idx")
    if os.path.exists(path) and os.path.exists(idxp):
        return path, idxp
    rec = recordio.MXIndexedRecordIO(idxp, path, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        label = float(rng.randint(0, 1000))
        header = recordio.IRHeader(0, label, i, 0)
        img = rng.randint(0, 256, (h, w, 3), dtype=np.uint8)
        if fmt == "jpeg":
            s = recordio.pack_img(header, img, quality=90)
        else:
            s = recordio.pack(header, img.tobytes())
        rec.write_idx(i, s)
    rec.close()
    return path, idxp


class RawRecordIter:
    """Minimal raw-uint8 record iterator: read, batch, ship — all
    augment/normalize deferred to the device (the TPU-side of the
    reference's decode pipeline split)."""

    def __init__(self, path_imgrec, path_imgidx, data_shape, batch_size):
        from mxnet_tpu import io as mio
        from mxnet_tpu import recordio
        self.batch_size = batch_size
        self._shape = data_shape            # (C, H, W) logical
        self._rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec,
                                               "r")
        self._keys = list(self._rec.keys)
        self._pos = 0
        self._unpack = recordio.unpack

    def reset(self):
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        import mxnet_tpu as mx
        from mxnet_tpu.io import DataBatch
        if self._pos + self.batch_size > len(self._keys):
            raise StopIteration
        c, h, w = self._shape
        datas = np.empty((self.batch_size, h, w, c), np.uint8)
        labels = np.empty((self.batch_size,), np.float32)
        for j in range(self.batch_size):
            header, payload = self._unpack(
                self._rec.read_idx(self._keys[self._pos + j]))
            datas[j] = np.frombuffer(payload, np.uint8).reshape(h, w, c)
            labels[j] = header.label
        self._pos += self.batch_size
        return DataBatch(data=[mx.nd.array(datas)],
                         label=[mx.nd.array(labels)])

    def next(self):
        return self.__next__()


def decode_scaling(tmpdir, n_images, hw, batch, threads_list):
    """Host-only decode+augment scaling curve vs preprocess_threads —
    the reference's parser→augmenter thread pipeline knob
    (src/io/iter_image_recordio_2.cc). No device involved: measures the
    iterator's own throughput."""
    from mxnet_tpu import io as mio
    rec_path, idx_path = make_packs(tmpdir, n_images, hw, "jpeg")
    base = None
    print(f"decode scaling (jpeg {hw[0]}x{hw[1]}, {n_images} imgs, "
          f"host cores={os.cpu_count()}):")
    for t in threads_list:
        it = mio.ImageRecordIter(
            path_imgrec=rec_path, path_imgidx=idx_path,
            data_shape=(3,) + hw, batch_size=batch, shuffle=True,
            rand_crop=True, rand_mirror=True, preprocess_threads=t,
            mean_r=127.5, mean_g=127.5, mean_b=127.5,
            std_r=127.5, std_g=127.5, std_b=127.5)
        for trial in range(2):                  # 2nd pass = warm page cache
            it.reset()
            n = 0
            t0 = time.perf_counter()
            for b in it:
                n += b.data[0].shape[0]
            dt = time.perf_counter() - t0
        ips = n / dt
        if t == threads_list[0]:
            base = ips
        print(f"  preprocess_threads={t}: {ips:8.1f} img/s "
              f"({ips / base:.2f}x vs {threads_list[0]} thread)")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--n-images", type=int, default=None)
    ap.add_argument("--format", choices=["jpeg", "raw", "both"],
                    default="both")
    ap.add_argument("--decode-scaling", action="store_true",
                    help="host-only preprocess_threads scaling curve")
    ap.add_argument("--threads", type=int, nargs="+", default=[1, 2, 4, 8])
    ap.add_argument("--tmpdir", default="/tmp/mxtpu_bench_data")
    args = ap.parse_args()

    if args.decode_scaling:
        batch = args.batch or 64
        n_images = args.n_images or 1024
        decode_scaling(args.tmpdir, n_images, (224, 224), batch,
                       args.threads)
        return

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, io as mio, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    on_tpu = jax.devices()[0].platform == "tpu"
    batch = args.batch or (256 if on_tpu else 8)
    n_images = args.n_images or (batch * (12 if on_tpu else 3))
    hw = (224, 224) if on_tpu else (64, 64)
    os.makedirs(args.tmpdir, exist_ok=True)

    class OnDeviceNormalize(gluon.HybridBlock):
        """uint8 NHWC -> normalized NCHW in the compute dtype, inside the
        compiled step (fuses into the first conv's operand read)."""

        def __init__(self, inner, dtype):
            super().__init__()
            self.inner = inner
            self._dtype = dtype

        def hybrid_forward(self, F, x):
            import jax
            # compute dtype applies inside the traced step (weights are
            # bf16 there); the eager shape-resolution pass runs fp32
            traced = isinstance(getattr(x, "_data", None),
                                jax.core.Tracer)
            x = F.cast(x, self._dtype if traced else "float32")
            x = F.transpose(x, axes=(0, 3, 1, 2))
            x = x * (1.0 / 127.5) - 1.0
            return self.inner(x)

    def run(fmt):
        rec_path, idx_path = make_packs(args.tmpdir, n_images, hw, fmt)
        net = vision.resnet50_v1() if on_tpu else \
            vision.resnet18_v1(classes=16, thumbnail=True)
        net.initialize()
        raw = fmt == "raw"
        block = OnDeviceNormalize(
            net, "bfloat16" if on_tpu else "float32") if raw else net
        trainer = parallel.ShardedTrainer(
            block, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "wd": 1e-4},
            mesh=parallel.make_mesh({"data": len(jax.devices())}),
            compute_dtype="bfloat16" if on_tpu else None)

        def fresh_iter():
            if raw:
                inner = RawRecordIter(rec_path, idx_path,
                                      (3,) + hw, batch)
            else:
                inner = mio.ImageRecordIter(
                    path_imgrec=rec_path, path_imgidx=idx_path,
                    data_shape=(3,) + hw, batch_size=batch,
                    shuffle=True, rand_mirror=True,
                    mean_r=127.5, mean_g=127.5, mean_b=127.5,
                    std_r=127.5, std_g=127.5, std_b=127.5)
            return mio.PrefetchingIter(inner, prefetch_depth=3)

        # warm: one epoch compiles the step and fills caches
        it = fresh_iter()
        n_warm = 0
        for b in it:
            trainer.step(b.data[0], b.label[0])
            n_warm += batch
            if n_warm >= 2 * batch:
                break
        # steady state: full pass, async dispatch, one sync at the end
        it = fresh_iter()
        n_done = 0
        t0 = time.perf_counter()
        loss = None
        for b in it:
            loss = trainer.step(b.data[0], b.label[0])
            n_done += batch
        np.asarray(loss.asnumpy())          # hard sync
        dt = time.perf_counter() - t0
        ips = n_done / dt / len(jax.devices())
        print(f"  {fmt:5s}: {ips:8.1f} img/s/chip "
              f"({n_done} imgs in {dt:.2f}s, batch={batch})")
        return ips

    print(f"platform={'tpu' if on_tpu else 'cpu'} "
          f"(host cores={os.cpu_count()})")
    fmts = ["jpeg", "raw"] if args.format == "both" else [args.format]
    for fmt in fmts:
        run(fmt)


if __name__ == "__main__":
    main()

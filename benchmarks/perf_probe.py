"""Piecewise ResNet-50 step profiler — where does the step time go?

Methodology note (axon/tunneled TPU): ``block_until_ready`` does not
honestly synchronize over the tunnel and a single dispatch costs ~90 ms
of round-trip latency. Every sub-program is therefore measured as a
k-iteration ``lax.scan`` (serialized by a carry data-dependency) with a
host transfer as the sync point, at two different k; the difference
cancels both the dispatch latency and the transfer cost:

    t_per_iter = (t(k2) - t(k1)) / (k2 - k1)

Usage: PYTHONPATH=. python benchmarks/perf_probe.py [--batch 256 512]
"""
from __future__ import annotations

import argparse
import time

import numpy as np


def _timed(call, iters=3):
    best = None
    for _ in range(iters):
        t0 = time.perf_counter()
        call()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def _scan_time(jit_fn, args, k1=2, k2=12):
    """jit_fn(k)(args...) -> scalar; returns seconds per inner iteration."""
    import jax
    f1, f2 = jit_fn(k1), jit_fn(k2)
    np.asarray(f1(*args))              # compile + warm
    np.asarray(f2(*args))
    t1 = _timed(lambda: np.asarray(f1(*args)))
    t2 = _timed(lambda: np.asarray(f2(*args)))
    return (t2 - t1) / (k2 - k1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, nargs="+", default=[256])
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import functional_apply

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    peak = 197e12 if on_tpu else 1e12     # v5e bf16 peak
    fwd_flops = 4.1e9                     # RN50 @224, per image
    print(f"platform={platform} devices={len(jax.devices())}")

    for batch in args.batch:
        net = vision.resnet50_v1()
        net.initialize()
        mesh = parallel.make_mesh({"data": len(jax.devices())})
        trainer = parallel.ShardedTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "wd": 1e-4},
            mesh=mesh, compute_dtype="bfloat16" if on_tpu else None)
        x_host = np.random.randn(batch, 3, 224, 224).astype(np.float32)
        y_host = np.random.randint(0, 1000, (batch,))
        trainer._prepare((x_host,))
        x = trainer._shard(x_host, trainer._batch_spec(4))
        y = trainer._shard(y_host, trainer._batch_spec(1))
        tr = [p._data[0]._data for p in trainer._trainable]
        aux = [p._data[0]._data for p in trainer._aux]

        cdt = jnp.bfloat16 if on_tpu else jnp.float32

        def cast_all(ws):
            return [w.astype(cdt) if jnp.issubdtype(w.dtype, jnp.floating)
                    else w for w in ws]

        def fwd_once(tr_, aux_, x_):
            outs, _, _ = functional_apply(
                net, jax.random.PRNGKey(0), tr_, aux_, [x_],
                training=True)   # training mode: batch stats, like the step
            return outs[0]

        def make_fwd(k):
            def run(tr_, aux_, x_):
                tr_ = cast_all(tr_)
                aux_ = cast_all(aux_)
                x_ = x_.astype(cdt)

                def body(c, _):
                    out = fwd_once(tr_, aux_, x_ + c * 1e-30)
                    return jnp.mean(out).astype(x_.dtype), None
                c, _ = jax.lax.scan(body, jnp.zeros((), x_.dtype),
                                    None, length=k)
                return c
            return jax.jit(run)

        def loss_of(tr_, aux_, x_, y_):
            outs, _, _ = functional_apply(
                net, jax.random.PRNGKey(0), tr_, aux_, [x_], training=True)
            logits = outs[0].astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            nll = lse - jnp.take_along_axis(
                logits, y_[:, None], axis=-1)[:, 0]
            return jnp.mean(nll)

        def make_grad(k):
            def run(tr_, aux_, x_, y_):
                tr_ = cast_all(tr_)
                aux_ = cast_all(aux_)
                x_ = x_.astype(cdt)

                def body(c, _):
                    g = jax.grad(loss_of)(
                        [w + (c * 1e-30).astype(w.dtype) for w in tr_],
                        aux_, x_, y_)
                    return jnp.mean(g[0]).astype(jnp.float32), None
                c, _ = jax.lax.scan(body, jnp.zeros(()), None, length=k)
                return c
            return jax.jit(run)

        t_fwd = _scan_time(make_fwd, (tr, aux, x))
        t_grad = _scan_time(make_grad, (tr, aux, x, y))

        # full fused train step (trainer.run_steps scan), same differencing
        def full_k(k):
            def call():
                np.asarray(
                    trainer.run_steps(x, y, num_steps=k).asnumpy())
            return call
        for k in (2, 12):
            full_k(k)()          # compile + warm both variants
        tf1 = _timed(full_k(2))
        tf2 = _timed(full_k(12))
        t_step = (tf2 - tf1) / 10

        n = len(jax.devices())

        def rep(name, t, mult):
            ips = batch / t / n
            mfu = mult * fwd_flops * ips / peak
            print(f"  batch={batch:4d} {name:12s} {t*1e3:8.2f} ms  "
                  f"{ips:8.0f} img/s/chip  MFU={mfu*100:5.1f}%")
        rep("forward", t_fwd, 1)
        rep("fwd+bwd", t_grad, 3)
        rep("full step", t_step, 3)


if __name__ == "__main__":
    main()

"""Benchmark of record: ResNet-50 training throughput (images/sec/chip).

Runs the flagship training step — the full fused SPMD program (forward,
softmax-CE loss, backward, SGD-momentum update) — on the available device
and reports steady-state throughput, per BASELINE.md's measurement protocol.

``vs_baseline`` is measured / governing-ceiling, where the ceiling is
BASELINE.md's physics-derived 3550 img/s/chip (HBM-bound: 59 GB/step
intrinsic traffic at ~819 GB/s — the binding constraint for RN50-bs256 on
one v5e; the 50%-MFU arithmetic ceiling is ≈8000 and not binding). On
non-TPU hosts the number is only a smoke signal.

Wedge-proof by construction (round-5 hardening; docs/perf_notes.md round-4
pitfall: a degraded tunnel can hang ``jax.devices()`` indefinitely, turning
a healthy benchmark into a silent rc=124):
  1. the device is dialed in a throwaway subprocess under a hard deadline,
     with retries + backoff;
  2. the benchmark body itself runs in a subprocess under a hard deadline;
  3. every failure path prints ONE structured JSON line (``error`` field set)
     instead of hanging, so the driver always records a parseable artifact;
  4. (round 6) the probe runs through ``mxnet_tpu.diagnostics.guard`` — the
     one sanctioned backend-dial path — and a journal SIGTERM finalizer
     emits a ``bench_killed`` diagnostic line carrying the last-known phase
     if the driver's outer kill lands first, so even an rc:124 artifact is
     attributable (docs/diagnostics.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} on success,
or {"metric", "value": null, ..., "error", "detail"} on a wedged device.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

METRIC = "resnet50_train_images_per_sec_per_chip"
BASELINE_CEILING = 3550.0  # BASELINE.md governing (HBM-bound) ceiling

PROBE_TIMEOUT_S = 150      # first TPU compile dial can take ~40s; 150 is slack
PROBE_BACKOFF_S = (0, 20, 45)  # len == number of probe attempts
BENCH_TIMEOUT_S = 840      # TPU body takes ~60s; 840 is deep slack
BENCH_TIMEOUT_CPU_S = 1500  # CPU smoke body measured 632-699s; the
                            # driver's own window is >= 30 min (r4 tail)


def _emit(obj: dict) -> None:
    sys.stdout.flush()
    print(json.dumps(obj), flush=True)


def _diagnostic(error: str, detail: str) -> dict:
    return {"metric": METRIC, "value": None, "unit": "images/sec/chip",
            "vs_baseline": None, "error": error, "detail": detail}


def _probe_deadline() -> float:
    # ONE resolver for the knob (guard.probe_deadline_s): a malformed
    # MXNET_TPU_PROBE_DEADLINE falls back to the default there instead
    # of crashing before any structured artifact is emitted
    from mxnet_tpu.diagnostics import guard
    if "MXNET_TPU_PROBE_DEADLINE" in os.environ:
        return guard.probe_deadline_s(None)
    return float(PROBE_TIMEOUT_S)


def _probe_device():
    """Dial ``jax.devices()`` in a throwaway subprocess under a deadline,
    via the diagnostics guard (mxnet_tpu/diagnostics/guard.py — the one
    sanctioned backend-dial path; per-attempt outcomes are journaled to
    stderr so the driver's tail capture shows *why*, not just rc).

    Returns ``{"platform": ..., "n": ...}`` on success, else ``None``
    after all attempts. Malformed child stdout (a dying tunnel truncating
    a write) is a failed attempt, never an exception — the
    one-structured-line contract survives it (ADVICE r5 low).
    """
    from mxnet_tpu.diagnostics import guard
    try:
        info = guard.probe_backend(deadline_s=_probe_deadline(),
                                   backoff_s=PROBE_BACKOFF_S)
    except guard.DeviceUnreachable as e:
        print(f"bench: {e}", file=sys.stderr)
        return None
    print(f"bench: device probe ok in {info['probe_s']}s -> "
          f"{info['n']}x {info['platform']}", file=sys.stderr)
    return info


def _parse_pallas_flag(argv) -> str | None:
    """``--pallas {on,off,auto}`` (or ``--pallas=X``): A/B switch for the
    guarded custom-kernel tier (docs/pallas.md). Returns the mode or
    None; the caller exports it as MXNET_TPU_PALLAS so the deadlined
    child body inherits the choice."""
    for i, arg in enumerate(argv):
        if arg.startswith("--pallas="):
            return arg.split("=", 1)[1].strip().lower()
        if arg == "--pallas":
            # a trailing flag with no value must be the structured
            # bad_flag diagnostic, not a silent default-auto A/B leg
            return (argv[i + 1].strip().lower() if i + 1 < len(argv)
                    else "")
    return None


def _run_body():
    """The actual benchmark (runs in the deadlined child process)."""
    import jax
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.diagnostics import Watchdog, get_journal
    from mxnet_tpu.gluon.model_zoo import vision

    # heartbeats to stderr (the parent relays its tail on timeout): a
    # mid-run tunnel degradation then shows phase + RSS + a stall dump.
    # stall_s=600: a healthy CPU-smoke compile is quiet for ~10 min, so
    # the dump must only fire when the 840s/1500s body deadline is near
    j = get_journal()
    Watchdog(journal=j, stall_s=600).start()
    j.set_phase("body_setup")
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    batch = 256 if on_tpu else 8
    steps = 8 if on_tpu else 2

    net = vision.resnet50_v1()
    net.initialize()
    mesh = parallel.make_mesh({"data": len(jax.devices())})
    # bf16 master weights+momentum: −0.6 GB/step of optimizer traffic on
    # an HBM-bound step (+1.9%, docs/perf_notes.md round 3); convergence-
    # gated against fp32 masters in tests/test_convergence.py
    # deferred-mode guard: the fused finiteness check + in-program skip
    # counters ride the measured step (so the artifact's throughput IS
    # the guarded number) with zero per-step host reads — skipped_steps
    # below is the one report-time fetch (docs/guardrails.md)
    from mxnet_tpu.guardrails import GuardConfig
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        mesh=mesh, compute_dtype="bfloat16" if on_tpu else None,
        master_dtype="bfloat16" if on_tpu else None,
        guard=GuardConfig(mode="deferred"))

    x_host = np.random.randn(batch, 3, 224, 224).astype(np.float32)
    y_host = np.random.randint(0, 1000, (batch,))
    # stage the batch on device once — the input pipeline's double-buffered
    # prefetch (SURVEY §2.5 #34 TPU equivalent) keeps steady-state steps free
    # of host→device transfers, which is what we measure here
    trainer._prepare((x_host,))
    x = trainer._shard(x_host, trainer._batch_spec(4))
    y = trainer._shard(y_host, trainer._batch_spec(1))

    # K steps per dispatch (lax.scan inside one program) so host/tunnel
    # dispatch latency never gates the measurement — the same program a
    # production input pipeline would run. Steady state = best of several
    # hard-synced windows (filters transient tunnel stalls; each window is
    # individually compute-honest per BASELINE.md's protocol).
    k = 10 if on_tpu else 2
    windows = 3 if on_tpu else 1
    j.set_phase("body_compile_warm")
    trainer.run_steps(x, y, num_steps=k).wait_to_read()     # compile+warm
    j.set_phase("body_measure")
    best_dt = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = trainer.run_steps(x, y, num_steps=k)
        np.asarray(loss.asnumpy())                          # hard sync
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)

    n_chips = len(jax.devices())
    img_per_sec_per_chip = batch * steps * k / best_dt / n_chips
    from mxnet_tpu import observability, pallas
    # telemetry provenance (docs/observability.md): compile counts/times
    # and step-phase p50/p95 ride the artifact — the ROADMAP item-2
    # hardware A/B needs exactly this on the first healthy window
    obs = observability.snapshot()
    comp = observability.compile_stats(obs)
    print(f"bench: compiles={comp['compiles']} "
          f"total={comp['total_ms']}ms by_site={comp['by_site']}",
          file=sys.stderr)
    _emit({
        "metric": METRIC,
        "value": round(img_per_sec_per_chip, 2),
        "unit": f"images/sec/chip ({platform}, batch={batch})",
        "vs_baseline": round(img_per_sec_per_chip / BASELINE_CEILING, 4),
        # per-op kernel-tier provenance (docs/pallas.md): which tier
        # each custom-kernel dispatch chose while building the measured
        # program, and why any fallback happened — an A/B number must
        # say which tier produced it
        "pallas": {"mode": pallas.mode(), "ops": pallas.tier_provenance()},
        # guardrail accounting (docs/guardrails.md): the fused guard's
        # in-program skip counter, fetched once at report time — a
        # non-zero count means the measured window trained on fewer
        # steps than dispatched (and guard overhead is visible in the
        # throughput number either way)
        "skipped_steps": int(trainer.skipped_steps),
        # observability snapshot: compile counts/times + step-phase
        # p50/p95 (always-on host metrics; tracing itself stays off
        # unless MXNET_TPU_TRACE is exported) — `doctor --metrics` on
        # this artifact reads it back
        "observability": obs,
    })


def main():
    pallas_mode = _parse_pallas_flag(sys.argv)
    if pallas_mode is not None:
        if pallas_mode not in ("on", "off", "auto"):
            _emit(_diagnostic("bad_flag",
                              f"--pallas must be on|off|auto, got "
                              f"{pallas_mode!r}"))
            return 2
        # env (not set_mode) so the deadlined child body inherits it
        os.environ["MXNET_TPU_PALLAS"] = pallas_mode
    if "--body" in sys.argv:
        return _run_body()

    # journaled breadcrumbs + SIGTERM finalizer: if the driver's outer
    # kill lands mid-run, the artifact still carries a parseable JSON
    # line with the last-known phase instead of a silent rc:124
    from mxnet_tpu.diagnostics import get_journal
    j = get_journal()
    j.install_handlers(final_cb=lambda: _emit(_diagnostic(
        "bench_killed",
        f"killed at phase {j.last_phase!r} before completion (outer "
        "deadline or signal); see stderr journal for breadcrumbs")))
    try:
        return _main_guarded(j)
    except Exception as e:
        # a plain Python crash must not masquerade as "killed by the
        # outer deadline": journal it, emit an honest crash diagnostic,
        # and re-raise so the traceback still reaches stderr
        j.crash(e)
        _emit(_diagnostic(
            "bench_crashed",
            f"{type(e).__name__}: {e} (at phase {j.last_phase!r})"))
        j.mark_clean()
        raise


def _main_guarded(j):
    with j.phase("bench_probe"):
        info = _probe_device()
    if info is None:
        _emit(_diagnostic(
            "device_unreachable",
            f"jax.devices() did not answer within {_probe_deadline():g}s "
            f"in any of {len(PROBE_BACKOFF_S)} attempts (backoffs "
            f"{PROBE_BACKOFF_S}s); TPU tunnel wedged — see "
            "docs/perf_notes.md round-4 pitfall"))
        j.mark_clean()
        return 0

    body_deadline = (BENCH_TIMEOUT_S if info["platform"] in ("tpu", "axon")
                     else BENCH_TIMEOUT_CPU_S)
    t0 = time.perf_counter()
    j.set_phase("bench_body")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--body"],
            capture_output=True, text=True, timeout=body_deadline)
    except subprocess.TimeoutExpired as e:
        tail = ((e.stderr or b"").decode("utf-8", "replace")
                if isinstance(e.stderr, bytes) else (e.stderr or ""))[-500:]
        _emit(_diagnostic(
            "bench_timeout",
            f"device probe was healthy ({info['n']}x {info['platform']}) but "
            f"the benchmark body exceeded {body_deadline}s — tunnel likely "
            f"degraded mid-run; stderr tail: {tail}"))
        j.mark_clean()
        return 0
    j.set_phase("bench_report")
    sys.stderr.write(proc.stderr[-2000:])
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        # validate before reprinting: a dying tunnel truncating a write
        # (or a library spraying JSON-shaped logs) must be a skipped
        # line, never a broken one-structured-JSON-line contract
        # (ADVICE r5 low, the guard._parse_info_line treatment)
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if not isinstance(parsed, dict) or "metric" not in parsed:
            continue
        print(line, flush=True)
        dt = time.perf_counter() - t0
        print(f"bench: body finished in {dt:.1f}s", file=sys.stderr)
        j.mark_clean()
        return 0 if proc.returncode == 0 else proc.returncode
    _emit(_diagnostic(
        "bench_body_failed",
        f"rc={proc.returncode}; no parseable metric line on stdout; "
        f"stderr tail: {proc.stderr[-500:]}"))
    j.mark_clean()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Benchmark of record: ResNet-50 training throughput (images/sec/chip).

Runs the flagship training step — the full fused SPMD program (forward,
softmax-CE loss, backward, SGD-momentum update) — on the available device
and reports steady-state throughput, per BASELINE.md's measurement protocol.

``vs_baseline`` is measured / governing-ceiling, where the ceiling is
BASELINE.md's physics-derived 3550 img/s/chip (HBM-bound: 59 GB/step
intrinsic traffic at ~819 GB/s — the binding constraint for RN50-bs256 on
one v5e; the 50%-MFU arithmetic ceiling is ≈8000 and not binding). On
non-TPU hosts the number is only a smoke signal.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import jax
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    batch = 256 if on_tpu else 8
    warmup = 3
    steps = 8 if on_tpu else 2

    net = vision.resnet50_v1()
    net.initialize()
    mesh = parallel.make_mesh({"data": len(jax.devices())})
    # bf16 master weights+momentum: −0.6 GB/step of optimizer traffic on
    # an HBM-bound step (+1.9%, docs/perf_notes.md round 3); convergence-
    # gated against fp32 masters in tests/test_convergence.py
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
        mesh=mesh, compute_dtype="bfloat16" if on_tpu else None,
        master_dtype="bfloat16" if on_tpu else None)

    x_host = np.random.randn(batch, 3, 224, 224).astype(np.float32)
    y_host = np.random.randint(0, 1000, (batch,))
    # stage the batch on device once — the input pipeline's double-buffered
    # prefetch (SURVEY §2.5 #34 TPU equivalent) keeps steady-state steps free
    # of host→device transfers, which is what we measure here
    trainer._prepare((x_host,))
    import mxnet_tpu as _mx
    x = trainer._shard(x_host, trainer._batch_spec(4))
    y = trainer._shard(y_host, trainer._batch_spec(1))

    # K steps per dispatch (lax.scan inside one program) so host/tunnel
    # dispatch latency never gates the measurement — the same program a
    # production input pipeline would run. Steady state = best of several
    # hard-synced windows (filters transient tunnel stalls; each window is
    # individually compute-honest per BASELINE.md's protocol).
    k = 10 if on_tpu else 2
    windows = 3 if on_tpu else 1
    trainer.run_steps(x, y, num_steps=k).wait_to_read()     # compile+warm
    best_dt = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = trainer.run_steps(x, y, num_steps=k)
        np.asarray(loss.asnumpy())                          # hard sync
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)

    n_chips = len(jax.devices())
    img_per_sec_per_chip = batch * steps * k / best_dt / n_chips
    baseline_ceiling = 3550.0  # BASELINE.md governing (HBM-bound) ceiling
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec_per_chip, 2),
        "unit": f"images/sec/chip ({platform}, batch={batch})",
        "vs_baseline": round(img_per_sec_per_chip / baseline_ceiling, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
